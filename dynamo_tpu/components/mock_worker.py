"""Mock worker: publishes synthetic KV metrics + events for dashboard and
aggregator testing without any model or TPU.

Emits everything a real worker's ``attach_kv_publishing`` loop does —
capacity/health gauges, the PR6 engine perf gauges, request outcome
counters, and a *realistic* ``phase_latency`` summary (cumulative bucket
counts included) — so the telemetry aggregator, SLO engine, and metric
renderers exercise the full pipeline in tier-1 without JAX or real
engines. :class:`MockWorkerStats` is the reusable sample generator tests
drive directly (deterministic seed, tunable TTFT/ITL centers — an
"induced latency regression" is one argument).

Reference counterpart: `components/metrics/src/bin/mock_worker.rs:158`.

Run:  python -m dynamo_tpu.components.mock_worker --namespace dynamo
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import random
import time
from typing import Dict, List, Optional

from dynamo_tpu.kv_router.protocols import ForwardPassMetrics

logger = logging.getLogger(__name__)


class LoadProfile:
    """Time-varying replay schedule for a mock worker (``--load-profile``).

    The schedule is a JSON list of segments, each a dict with a ``t`` (seconds
    from start) plus any of the knobs it overrides from that point on::

        [{"t": 0,  "ttft_ms": 100, "itl_ms": 20},
         {"t": 30, "ttft_ms": 9000, "queue_depth": 40, "error_rate": 0.2},
         {"t": 60, "ttft_ms": 100, "queue_depth": 0, "error_rate": 0}]

    Segments apply as a step function (the last segment with ``t`` ≤ elapsed
    wins); unknown keys are ignored so schedules stay forward-compatible.
    Elapsed time is supplied by the caller (tick index × interval in
    ``run_mock_worker``) so a replay is deterministic — the same schedule
    and seed produce byte-identical metric streams, which is what planner
    drills and the traffic simulator's regression legs need.
    """

    KEYS = ("ttft_ms", "itl_ms", "queue_depth", "error_rate", "requests")

    def __init__(self, segments: List[dict]):
        cleaned = []
        for seg in segments:
            if not isinstance(seg, dict):
                raise ValueError("load profile segments must be dicts")
            cleaned.append(dict(seg, t=float(seg.get("t", 0.0))))
        self.segments = sorted(cleaned, key=lambda s: s["t"])
        if not self.segments:
            raise ValueError("load profile needs at least one segment")

    @classmethod
    def from_file(cls, path: str) -> "LoadProfile":
        import json

        with open(path) as f:
            return cls(json.load(f))

    def at(self, elapsed: float) -> dict:
        """Merged knob dict in effect at ``elapsed`` seconds (each knob keeps
        the value from the latest segment that set it)."""
        state: Dict[str, float] = {}
        for seg in self.segments:
            if seg["t"] > elapsed:
                break
            for k in self.KEYS:
                if k in seg:
                    state[k] = seg[k]
        return state


class MockWorkerStats:
    """Synthetic per-worker telemetry state.

    Maintains cumulative phase-latency histograms in exactly the shape
    ``tracing.phase_summary()`` publishes (bucket bounds from
    ``tracing.PHASE_BUCKETS``, cumulative counts, bucket-interpolated
    quantiles), plus request counters — so consumers can't tell a mock
    from a real worker on the wire.
    """

    def __init__(
        self,
        seed: int = 0,
        ttft_ms: float = 250.0,
        itl_ms: float = 20.0,
        slots_total: int = 16,
        blocks_total: int = 1024,
        spec_accept_rate: float = 0.0,
        kv_quantized: bool = False,
        role: str = "decode",
        tenants: Optional[Dict[str, int]] = None,
        resume_total: int = 0,
        resume_failed: int = 0,
        migrations_total: int = 0,
        migrations_failed: int = 0,
        migrate_kv_blocks_moved: int = 0,
        control_plane_state: str = "connected",
        bus_dropped_events: int = 0,
        integrity_failures: int = 0,
        watchdog_trips: int = 0,
        health_state: str = "healthy",
        dispatch_device_us: float = 0.0,
        jit_recompiles: int = 6,
        device_idle_frac: float = 0.0,
        dispatch_us_per_token: float = 0.0,
        straggler_state: str = "ok",
    ):
        from dynamo_tpu.runtime.tracing import PHASE_BUCKETS

        self.rng = random.Random(seed)
        self.ttft_ms = ttft_ms
        self.itl_ms = itl_ms
        self.slots_total = slots_total
        self.blocks_total = blocks_total
        # pool role for the cluster rollup's per-pool breakdown (what the
        # planner resizes); queue_depth overrides num_requests_waiting and
        # kv_occupancy overrides the jittered KV fill when a load profile
        # (or the traffic simulator) drives the worker shape exactly
        self.role = role
        self.queue_depth: Optional[int] = None
        self.kv_occupancy: Optional[float] = None
        self.bounds = PHASE_BUCKETS + (float("inf"),)
        self._counts: Dict[str, List[int]] = {}
        self._sums: Dict[str, float] = {}
        self._totals: Dict[str, int] = {}
        self.requests_total = 0
        self.requests_errored = 0
        self.active = 0
        self.started = time.monotonic()
        # speculative decoding (PR7): an engine with speculation off reports
        # 0.0 and zero counters — the mock defaults match that; set a rate
        # to exercise the dashboard columns + cluster rollup
        self.spec_accept_rate = max(0.0, min(1.0, spec_accept_rate))
        self.kv_quantized = bool(kv_quantized)
        self.spec_drafted = 0
        self.spec_accepted = 0
        # mid-stream resume drill (docs/resilience.md): report nonzero
        # recovery counters so the dynamo_*_resume_* gauges and the cluster
        # rollup's resume sums can be exercised without killing workers
        self.resume_total = max(int(resume_total), 0)
        self.resume_failed = max(int(resume_failed), 0)
        # live-migration drill (docs/resilience.md §Live migration): report
        # nonzero drain-migration counters so the dynamo_*_migrations_*
        # gauges and the cluster rollup sums can be exercised without
        # draining real workers
        self.migrations_total = max(int(migrations_total), 0)
        self.migrations_failed = max(int(migrations_failed), 0)
        self.migrate_kv_blocks_moved = max(int(migrate_kv_blocks_moved), 0)
        # control-plane blackout drill: report a stale/disconnected view so
        # `llmctl control-plane status` exit-2 and the dynamo_*_control_*
        # gauges can be exercised without killing a statestore
        self.control_plane_state = control_plane_state
        self.bus_dropped_events = max(int(bus_dropped_events), 0)
        # silent-corruption drill (docs/resilience.md §Silent corruption):
        # report integrity trip counters and/or a quarantined health state
        # so the dynamo_*_kv_integrity_* gauges, the rollup's quarantine
        # counts, and the llmctl quar= column render without corrupting a
        # real worker
        self.integrity_failures = max(int(integrity_failures), 0)
        self.watchdog_trips = max(int(watchdog_trips), 0)
        self.health_state = (
            health_state
            if health_state in ("healthy", "degraded", "unhealthy",
                                "quarantined", "suspect")
            else "healthy"
        )
        # fail-slow drill (docs/resilience.md §Fail-slow): report a nonzero
        # normalized dispatch EWMA and/or a latched verdict so the
        # dynamo_*_dispatch_us_per_token / straggler gauges, the rollup's
        # suspect counts, and the `llmctl cluster status` slow= column
        # render without actually slowing a worker. The sample counter
        # grows per tick (see tick()) so the arbiter's freshness check can
        # be drilled too.
        self.dispatch_us_per_token = max(float(dispatch_us_per_token), 0.0)
        self.straggler_state = (
            straggler_state
            if straggler_state in ("ok", "suspect", "confirmed")
            else "ok"
        )
        self.straggler_samples = 0
        # profiling-plane drill (docs/observability.md §Profiling): report
        # a nonzero dispatch device-time p95 / idle fraction / recompile
        # count so the dynamo_{worker,cluster}_dispatch_* gauges and
        # `llmctl profile` aggregation render TPU-less. A healthy engine
        # compiles its variants once at boot — jit_recompiles defaults to
        # that shape; raise it to drill the recompile-storm dashboards.
        self.dispatch_device_us = max(float(dispatch_device_us), 0.0)
        self.jit_recompiles = max(int(jit_recompiles), 0)
        self.device_idle_frac = min(max(float(device_idle_frac), 0.0), 1.0)
        # multi-tenant QoS drill (docs/qos.md): tenant → per-tick request
        # share. Each tick splits its requests across tenants by share and
        # grows per-tenant counters + occupancy splits, so aggregator /
        # llmctl tenant views can be exercised without chips. One tenant
        # can be marked abusive via share 0 below (all rate-limited).
        self.tenants: Dict[str, int] = dict(tenants or {})
        # tenant → [admitted, rate_limited] cumulative
        self._tenant_counts: Dict[str, List[int]] = {
            t: [0, 0] for t in self.tenants
        }

    def _observe(self, phase: str, seconds: float) -> None:
        counts = self._counts.setdefault(phase, [0] * len(self.bounds))
        for i, b in enumerate(self.bounds):
            if seconds <= b:
                counts[i] += 1  # cumulative, like llm/http/metrics.Histogram
        self._sums[phase] = self._sums.get(phase, 0.0) + seconds
        self._totals[phase] = self._totals.get(phase, 0) + 1

    def _jitter(self, center_ms: float) -> float:
        # mild right-skew: most samples near center, occasional 2-3x tail
        base = center_ms * (0.7 + 0.6 * self.rng.random())
        if self.rng.random() < 0.05:
            base *= 1.0 + 2.0 * self.rng.random()
        return base / 1e3

    def tick(self, requests: int = 8, error_rate: float = 0.0) -> None:
        """Simulate one metrics interval of traffic: ``requests`` finished
        requests (one TTFT + ~16 inter-token gaps each). With ``tenants``
        configured, each tenant additionally books ``share`` admitted
        requests per tick — except share-0 tenants, which model a fully
        throttled (100% rate-limited) abuser so the `llmctl tenant
        status` exit-2 path can be drilled without chips."""
        for t, share in self.tenants.items():
            counts = self._tenant_counts.setdefault(t, [0, 0])
            if share > 0:
                counts[0] += share
            else:
                counts[1] += 4  # sustained 100% throttle
        for _ in range(requests):
            self.requests_total += 1
            if self.rng.random() < error_rate:
                self.requests_errored += 1
            self._observe("ttft", self._jitter(self.ttft_ms))
            for _ in range(16):
                self._observe("inter_token", self._jitter(self.itl_ms))
            if self.spec_accept_rate > 0.0:
                # synthetic drafting: ~4 drafts per emitted token batch,
                # accepted at the configured rate (deterministic-seeded);
                # per-request rate feeds the spec_accept phase histogram
                # exactly like a real engine's _record_phase_spans
                drafted = 4 * 16
                accepted = sum(
                    1 for _ in range(drafted)
                    if self.rng.random() < self.spec_accept_rate
                )
                self.spec_drafted += drafted
                self.spec_accepted += accepted
                self._observe("spec_accept", accepted / drafted)
        self.active = max(
            0, min(self.slots_total, self.active + self.rng.randint(-3, 3))
        )
        if self.dispatch_us_per_token > 0.0:
            # a live detector's sample counter grows every dispatch (~1
            # prefill + 16 decode steps per request here) — fresh tick
            # over tick, which is what the arbiter's freshness gate needs
            self.straggler_samples += requests * 17

    def observe_request(
        self,
        ttft_ms: Optional[float] = None,
        itl_ms: Optional[float] = None,
        n_itl: int = 8,
        errored: bool = False,
        count: bool = True,
    ) -> None:
        """One finished request with *exact* latencies — no jitter. The
        traffic simulator (tools/traffic_sim.py) computes per-request TTFT
        from its queue model and needs the published histograms to reflect
        it deterministically; ``tick`` stays the jittered path for
        dashboard-shaped traffic. ``count=False`` records latency samples
        without bumping the request counters (the simulator books each
        request's TTFT on a prefill worker and its ITL on a decode worker —
        the request must count once, not twice)."""
        if count:
            self.requests_total += 1
        if errored:
            self.requests_errored += 1
        if ttft_ms is not None:
            self._observe("ttft", max(ttft_ms, 0.0) / 1e3)
        if itl_ms is not None:
            for _ in range(max(n_itl, 0)):
                self._observe("inter_token", max(itl_ms, 0.0) / 1e3)

    def phase_latency(self) -> dict:
        from dynamo_tpu.runtime.tracing import _bucket_quantile

        out: Dict[str, dict] = {}
        for phase, counts in self._counts.items():
            total = self._totals[phase]
            if total == 0:
                continue
            out[phase] = {
                "count": total,
                "sum_s": round(self._sums[phase], 6),
                "p50_ms": _bucket_quantile(self.bounds, counts, total, 0.50),
                "p95_ms": _bucket_quantile(self.bounds, counts, total, 0.95),
                "p99_ms": _bucket_quantile(self.bounds, counts, total, 0.99),
                "buckets": list(counts),
            }
        return out

    def metrics(self, model: str = "mock-model") -> ForwardPassMetrics:
        kv_fill = (
            self.kv_occupancy if self.kv_occupancy is not None
            else self.active / self.slots_total + self.rng.random() * 0.2
        )
        blocks = int(self.blocks_total * min(max(kv_fill, 0.0), 1.0))
        waiting = (
            int(self.queue_depth) if self.queue_depth is not None
            else self.rng.randint(0, 4)
        )
        itl_s = max(self.itl_ms, 1e-3) / 1e3
        tenants = None
        if self.tenants:
            total_share = sum(s for s in self.tenants.values() if s > 0) or 1
            tenants = {}
            for t, share in self.tenants.items():
                frac = max(share, 0) / total_share
                counts = self._tenant_counts.get(t, [0, 0])
                tenants[t] = {
                    "class": "standard",
                    "active_slots": int(self.active * frac),
                    "queue_depth": int(waiting * frac),
                    "kv_blocks": int(blocks * frac),
                    "admitted": counts[0],
                    "rate_limited": counts[1],
                }
        return ForwardPassMetrics(
            request_active_slots=self.active,
            request_total_slots=self.slots_total,
            kv_active_blocks=blocks,
            kv_total_blocks=self.blocks_total,
            num_requests_waiting=waiting,
            gpu_cache_usage_perc=blocks / self.blocks_total,
            gpu_prefix_cache_hit_rate=self.rng.random() * 0.6,
            # exercise the overload dashboard columns too
            rpc_queue_depth=self.active + waiting,
            shed_requests=0,
            draining=0,
            # health plane columns (deterministic: the mock exists so
            # dashboards render the fields, not to flap; --health-state
            # quarantined drills the integrity plane's rendering)
            health_state=self.health_state,
            stalls_total=0,
            reaped_requests_total=0,
            # tracing + telemetry planes (PR5/PR6)
            phase_latency=self.phase_latency(),
            decode_tokens_per_s=round(self.active / itl_s, 1),
            step_time_ms=round(self.itl_ms * (0.9 + 0.2 * self.rng.random()), 2),
            batch_slot_util=round(self.active / self.slots_total, 3),
            jit_recompiles=self.jit_recompiles,
            dispatch_device_us_p95=round(self.dispatch_device_us, 1),
            # host overhead rides the drill at a realistic ~15% of device
            dispatch_host_overhead_us_p95=round(
                self.dispatch_device_us * 0.15, 1
            ),
            device_idle_frac=round(self.device_idle_frac, 4),
            kv_peak_occupancy_perc=round(
                max(blocks / self.blocks_total, 0.5), 3
            ),
            requests_total=self.requests_total,
            requests_errored=self.requests_errored,
            # speculative decoding + KV layout (PR7)
            spec_accept_rate=round(self.spec_accept_rate, 4),
            spec_drafted_tokens=self.spec_drafted,
            spec_accepted_tokens=self.spec_accepted,
            kv_quantized=int(self.kv_quantized),
            resume_total=self.resume_total,
            resume_failed_total=self.resume_failed,
            migrations_total=self.migrations_total,
            migrations_failed_total=self.migrations_failed,
            migrate_kv_blocks_moved_total=self.migrate_kv_blocks_moved,
            kv_integrity_failures_total=self.integrity_failures,
            watchdog_trips_total=self.watchdog_trips,
            control_plane_state=self.control_plane_state,
            bus_dropped_events=self.bus_dropped_events,
            # fail-slow plane drill fields (zeros/"ok" = plane off, like a
            # real DYN_TPU_STRAGGLER=0 worker)
            dispatch_us_per_token_ewma=round(self.dispatch_us_per_token, 1),
            straggler_samples_total=self.straggler_samples,
            straggler_state=self.straggler_state,
            uptime_s=round(time.monotonic() - self.started, 3),
            model=model,
            role=self.role,
            tenants=tenants,
        )

    def apply_profile(self, state: dict) -> int:
        """Apply a :class:`LoadProfile` state dict; returns the per-tick
        request count (default 8) so the caller drives ``tick`` with it."""
        if "ttft_ms" in state:
            self.ttft_ms = float(state["ttft_ms"])
        if "itl_ms" in state:
            self.itl_ms = float(state["itl_ms"])
        if "queue_depth" in state:
            self.queue_depth = max(int(state["queue_depth"]), 0)
        return max(int(state.get("requests", 8)), 0)


def parse_tenant_shares(raw: Optional[str]) -> Optional[Dict[str, int]]:
    """``--tenants "acme:6,bigco:2,crawler:0"`` → {name: share}. Malformed
    entries are skipped; an empty result means no tenant emulation."""
    if not raw:
        return None
    out: Dict[str, int] = {}
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, share = part.partition(":")
        name = name.strip()
        if not name:
            continue
        if not share.strip():
            out[name] = 1  # bare name: one request/tick
            continue
        try:
            out[name] = max(int(share), 0)
        except ValueError:
            continue  # malformed share: skip, as documented
    return out or None


async def run_mock_worker(
    drt,
    namespace: str,
    interval: float = 1.0,
    worker_id: str | None = None,
    model: str = "mock-model",
    ttft_ms: float = 250.0,
    itl_ms: float = 20.0,
    spec_accept_rate: float = 0.0,
    kv_quantized: bool = False,
    role: str = "decode",
    profile: Optional[LoadProfile] = None,
    tenants: Optional[Dict[str, int]] = None,
    resume_total: int = 0,
    resume_failed: int = 0,
    migrations_total: int = 0,
    migrations_failed: int = 0,
    control_plane_state: str = "connected",
    integrity_failures: int = 0,
    watchdog_trips: int = 0,
    health_state: str = "healthy",
    dispatch_device_us: float = 0.0,
    jit_recompiles: int = 6,
    device_idle_frac: float = 0.0,
    dispatch_us_per_token: float = 0.0,
    straggler_state: str = "ok",
) -> None:
    from dynamo_tpu.runtime.distributed import KV_METRICS_SUBJECT

    ns = drt.namespace(namespace)
    wid = worker_id or f"mock-{drt.worker_id}"
    stats = MockWorkerStats(
        seed=hash(wid) & 0xFFFF, ttft_ms=ttft_ms, itl_ms=itl_ms,
        spec_accept_rate=spec_accept_rate, kv_quantized=kv_quantized,
        role=role, tenants=tenants,
        resume_total=resume_total, resume_failed=resume_failed,
        migrations_total=migrations_total,
        migrations_failed=migrations_failed,
        migrate_kv_blocks_moved=migrations_total * 8,
        control_plane_state=control_plane_state,
        integrity_failures=integrity_failures,
        watchdog_trips=watchdog_trips,
        health_state=health_state,
        dispatch_device_us=dispatch_device_us,
        jit_recompiles=jit_recompiles,
        device_idle_frac=device_idle_frac,
        dispatch_us_per_token=dispatch_us_per_token,
        straggler_state=straggler_state,
    )
    tick_no = 0
    while True:
        requests, error_rate = 8, 0.0
        if profile is not None:
            # elapsed from the tick index, NOT the wall clock: a loaded CI
            # box must replay the same schedule the same way every run
            state = profile.at(tick_no * interval)
            requests = stats.apply_profile(state)
            error_rate = float(state.get("error_rate", 0.0))
        stats.tick(requests=requests, error_rate=error_rate)
        tick_no += 1
        await ns.publish(
            KV_METRICS_SUBJECT,
            {"worker_id": wid, "metrics": stats.metrics(model).to_dict()},
        )
        await asyncio.sleep(interval)


def main() -> None:
    p = argparse.ArgumentParser(description="dynamo_tpu mock worker")
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--statestore", default=None)
    p.add_argument("--bus", default=None)
    p.add_argument("--interval", type=float, default=1.0)
    p.add_argument("--worker-id", default=None)
    p.add_argument("--model", default="mock-model")
    p.add_argument("--ttft-ms", type=float, default=250.0,
                   help="synthetic TTFT center (regression drills: raise it)")
    p.add_argument("--itl-ms", type=float, default=20.0)
    p.add_argument("--spec-accept-rate", type=float, default=0.0,
                   help="synthetic speculative-draft acceptance rate (0..1; "
                        "0 = speculation off, like a real default engine)")
    p.add_argument("--kv-quantized", action="store_true",
                   help="report the int8-KV flag (exercises the dashboard "
                        "column without a real quantized pool)")
    p.add_argument("--role", default="decode",
                   choices=("decode", "prefill", "frontend"),
                   help="pool role for the cluster rollup's per-pool "
                        "breakdown (what the planner resizes)")
    p.add_argument("--load-profile", default=None,
                   help="JSON schedule replaying time-varying TTFT/ITL/"
                        "queue/error-rate (planner drills without a TPU; "
                        "see LoadProfile docstring for the format)")
    p.add_argument("--tenants", default=None,
                   help="per-tenant request shares, e.g. 'acme:6,bigco:2,"
                        "crawler:0' — share 0 models a fully rate-limited "
                        "abuser (drills llmctl tenant status / the "
                        "dynamo_tenant_* gauges without chips)")
    p.add_argument("--resume-total", type=int, default=0,
                   help="report N mid-stream resumes (drills the "
                        "dynamo_*_resume_total gauges without killing "
                        "workers)")
    p.add_argument("--resume-failed", type=int, default=0,
                   help="report N failed resume recoveries")
    p.add_argument("--migrations-total", type=int, default=0,
                   help="report N drain-time live migrations (drills the "
                        "dynamo_*_migrations_* gauges and llmctl cluster "
                        "status migr= column without draining workers)")
    p.add_argument("--migrations-failed", type=int, default=0,
                   help="report N migrations that degraded to resume")
    p.add_argument("--integrity-failures", type=int, default=0,
                   help="report N KV integrity checksum failures (drills "
                        "the dynamo_*_kv_integrity_* gauges and the llmctl "
                        "quar= column without corrupting a worker)")
    p.add_argument("--watchdog-trips", type=int, default=0,
                   help="report N output-watchdog lane trips")
    p.add_argument("--health-state", default="healthy",
                   choices=("healthy", "degraded", "unhealthy",
                            "quarantined", "suspect"),
                   help="report this health state (quarantined drills the "
                        "rollup's quarantine counts + planner drain "
                        "decisions TPU-lessly; suspect drills the "
                        "fail-slow soft-demotion rendering)")
    p.add_argument("--control-plane-state", default="connected",
                   choices=("connected", "stale", "disconnected"),
                   help="report this control-plane view (drills `llmctl "
                        "control-plane status` exit-2 and the "
                        "dynamo_*_control_plane gauges without killing a "
                        "statestore)")
    p.add_argument("--dispatch-device-us", type=float, default=0.0,
                   help="report this decode-dispatch device-time p95 "
                        "(drills the dynamo_*_dispatch_* profiling gauges "
                        "and `llmctl profile` aggregation TPU-lessly)")
    p.add_argument("--jit-recompiles", type=int, default=6,
                   help="report this cumulative jit-compile count (raise "
                        "it to drill recompile-storm dashboards)")
    p.add_argument("--device-idle-frac", type=float, default=0.0,
                   help="report this device idle fraction (the profiling "
                        "runbook's read-first gauge)")
    p.add_argument("--dispatch-us-per-token", type=float, default=0.0,
                   help="report this normalized dispatch-latency EWMA "
                        "(us/token; drills the fail-slow arbiter and the "
                        "dynamo_*_dispatch_us_per_token gauges — run N "
                        "mocks and give one a 10x value to watch it go "
                        "suspect)")
    p.add_argument("--straggler-state", default="ok",
                   choices=("ok", "suspect", "confirmed"),
                   help="report this latched fail-slow verdict (drills the "
                        "rollup's suspect counts and the llmctl cluster "
                        "status slow= column without a live arbiter)")
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    profile = (
        LoadProfile.from_file(args.load_profile)
        if args.load_profile else None
    )

    async def run():
        from dynamo_tpu.runtime.distributed import DistributedRuntime

        drt = await DistributedRuntime.create(
            statestore_url=args.statestore, bus_url=args.bus
        )
        await run_mock_worker(
            drt, args.namespace, interval=args.interval,
            worker_id=args.worker_id, model=args.model,
            ttft_ms=args.ttft_ms, itl_ms=args.itl_ms,
            spec_accept_rate=args.spec_accept_rate,
            kv_quantized=args.kv_quantized,
            role=args.role, profile=profile,
            tenants=parse_tenant_shares(args.tenants),
            resume_total=args.resume_total,
            resume_failed=args.resume_failed,
            migrations_total=args.migrations_total,
            migrations_failed=args.migrations_failed,
            control_plane_state=args.control_plane_state,
            integrity_failures=args.integrity_failures,
            watchdog_trips=args.watchdog_trips,
            health_state=args.health_state,
            dispatch_device_us=args.dispatch_device_us,
            jit_recompiles=args.jit_recompiles,
            device_idle_frac=args.device_idle_frac,
            dispatch_us_per_token=args.dispatch_us_per_token,
            straggler_state=args.straggler_state,
        )

    asyncio.run(run())


if __name__ == "__main__":
    main()
