"""Standalone KV-aware router service.

Serves ``{"token_ids": [...]}` → ``{"worker_id", "overlap_blocks",
"prefix_hit_rate"}`` as a distributed endpoint, feeding its radix tree from
the namespace's ``kv_events``/``kv_metrics`` streams — so frontends (or any
component) can delegate routing decisions instead of embedding the router
in their client.

Reference counterpart: the `router` component binary
(`components/router/src/main.rs:50-95`: KvRouter wrapped in an Ingress
serving `generate`).

Run:  python -m dynamo_tpu.components.router --namespace dynamo
Call: dyn://{ns}.router.schedule
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging

from dynamo_tpu.kv_router.protocols import (
    ForwardPassMetrics,
    RouterEvent,
    ScheduleDecision,
    ScheduleRequest,
)
from dynamo_tpu.kv_router.router import KvRouter
from dynamo_tpu.runtime.annotated import Annotated
from dynamo_tpu.runtime.engine import AsyncEngine, Context

logger = logging.getLogger(__name__)


class RouterEngine(AsyncEngine):
    """AsyncEngine facade over KvRouter: one request in, one decision out."""

    def __init__(self, router: KvRouter):
        self.router = router

    async def generate(self, request: Context):
        data = request.data
        req = ScheduleRequest.from_dict(data) if isinstance(data, dict) else None
        if req is None or not req.token_ids:
            yield Annotated.from_error("router request needs token_ids")
            return
        decision = self.router.schedule(req.token_ids)
        if decision is None:
            yield Annotated.from_error("no workers registered")
            return
        blocks = (
            len(req.token_ids) + self.router.block_size - 1
        ) // self.router.block_size
        yield Annotated.from_data(
            ScheduleDecision(
                worker_id=decision.worker_id,
                overlap_blocks=decision.overlap_blocks,
                prefix_hit_rate=decision.overlap_blocks / max(blocks, 1),
            ).to_dict()
        )


async def run_router(drt, namespace: str, block_size: int = 16) -> None:
    """Register the router endpoint and feed it from the event plane."""
    from dynamo_tpu.runtime.distributed import (
        KV_EVENTS_SUBJECT,
        KV_METRICS_SUBJECT,
        hit_rate_sink,
        resubscribe_forever,
    )

    import time as _time

    router = KvRouter(block_size)
    ns = drt.namespace(namespace)
    router.on_hit_rate = hit_rate_sink(ns)
    last_seen: dict = {}

    feed_alive = [0.0]  # time of the last metrics delivery from ANY worker

    def on_metrics(d):
        wid = d["worker_id"]
        now = _time.monotonic()
        last_seen[wid] = now
        feed_alive[0] = now
        router.update_worker_metrics(wid, ForwardPassMetrics.from_dict(d["metrics"]))

    async def expire_dead_workers(expiry: float = 15.0):
        # workers publish metrics every ~1s; silence means death (the
        # embedded router learns this from the instance watch — standalone,
        # metrics staleness is the liveness signal). Before purging, confirm
        # the BUS itself is reachable: total silence with a dead bus is a
        # feed outage, but with a healthy bus even a lone silent worker is
        # genuinely gone.
        while True:
            await asyncio.sleep(expiry / 3)
            cutoff = _time.monotonic() - expiry
            stale = [w for w, t in last_seen.items() if t < cutoff]
            if not stale:
                continue
            if feed_alive[0] < cutoff:
                try:
                    await drt.bus.queue_len("__router_liveness_probe__")
                except asyncio.CancelledError:
                    raise
                except Exception:
                    # bus unreachable: feed outage, keep state
                    logger.debug("router liveness probe failed", exc_info=True)
                    continue
            for wid in stale:
                logger.info("worker %s silent > %.0fs: purging from router", wid, expiry)
                router.remove_worker(wid)
                del last_seen[wid]

    feeds = [
        asyncio.create_task(resubscribe_forever(
            ns, KV_EVENTS_SUBJECT,
            lambda d: router.apply_event(RouterEvent.from_dict(d)),
        )),
        asyncio.create_task(resubscribe_forever(ns, KV_METRICS_SUBJECT, on_metrics)),
        asyncio.create_task(expire_dead_workers()),
    ]

    component = ns.component("router")
    await component.create_service()
    endpoint = component.endpoint("schedule")
    info = await endpoint.serve(RouterEngine(router))
    logger.info("router service %s at dyn://%s.router.schedule", info.worker_id, namespace)
    try:
        await drt.wait_closed()
    finally:
        for t in feeds:
            t.cancel()


def main() -> None:
    p = argparse.ArgumentParser(description="dynamo_tpu standalone KV router")
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--kv-block-size", type=int, default=16)
    p.add_argument("--statestore", default=None)
    p.add_argument("--bus", default=None)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    async def run():
        from dynamo_tpu.runtime.distributed import DistributedRuntime
        from dynamo_tpu.runtime.worker import serve_until_shutdown

        drt = await DistributedRuntime.create(
            statestore_url=args.statestore, bus_url=args.bus
        )
        task = asyncio.create_task(run_router(drt, args.namespace, args.kv_block_size))
        await serve_until_shutdown(drt)
        task.cancel()

    asyncio.run(run())


if __name__ == "__main__":
    main()
