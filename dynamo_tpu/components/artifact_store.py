"""Artifact store: upload/list/fetch built graph bundles + deployment CRUD.

The deploy half of the build→deploy story: `dynamo build` packages a graph
into a bundle; this service stores bundle tarballs content-addressed by
digest, keeps their manifests queryable, and records *deployments* (a
named intent to run a bundle with a config) that an operator or controller
reconciles onto machines.

Reference parity: the api-store (deploy/dynamo/api-store/
ai_dynamo_store/api/{dynamo,components,deployments}.py) — re-designed as
a dependency-free aiohttp service with disk-backed artifacts.

HTTP surface:
    POST   /v1/artifacts            body = .tar.gz, headers: X-Bundle-Name
    GET    /v1/artifacts            list (name, digest, size, manifest)
    GET    /v1/artifacts/{digest}   download the tarball
    DELETE /v1/artifacts/{digest}
    POST   /v1/deployments          {"name", "artifact", "config"}
    GET    /v1/deployments[/name]
    DELETE /v1/deployments/{name}

Run:  python -m dynamo_tpu.components.artifact_store --root /var/lib/dynamo
"""

from __future__ import annotations

import argparse
import asyncio
import hashlib
import io
import json
import logging
import os
import tarfile
import time
from typing import Optional

from aiohttp import web

logger = logging.getLogger(__name__)

MAX_BUNDLE_BYTES = 512 << 20


class ArtifactStore:
    def __init__(self, root: str):
        self.root = root
        os.makedirs(os.path.join(root, "artifacts"), exist_ok=True)
        os.makedirs(os.path.join(root, "deployments"), exist_ok=True)

    # -- artifacts -----------------------------------------------------------

    def _artifact_path(self, digest: str) -> str:
        if not digest.isalnum():
            raise web.HTTPBadRequest(text="bad digest")
        return os.path.join(self.root, "artifacts", digest)

    def put_artifact(self, name: str, blob: bytes) -> dict:
        digest = hashlib.sha256(blob).hexdigest()[:32]
        path = self._artifact_path(digest)
        manifest = self._extract_manifest(blob)
        meta = {
            "name": name,
            "digest": digest,
            "size": len(blob),
            "manifest": manifest,
            "created_at": time.time(),
        }
        os.makedirs(path, exist_ok=True)
        # atomic: a re-POST of an existing digest must never let a reader
        # stream a half-rewritten tarball
        tmp_blob = os.path.join(path, ".bundle.tar.gz.tmp")
        with open(tmp_blob, "wb") as f:
            f.write(blob)
        os.replace(tmp_blob, os.path.join(path, "bundle.tar.gz"))
        # atomic rename: put_artifact runs on a worker thread, and a
        # concurrent list_artifacts on the event loop must never see a
        # half-written meta.json
        tmp = os.path.join(path, ".meta.json.tmp")
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, os.path.join(path, "meta.json"))
        return meta

    @staticmethod
    def _extract_manifest(blob: bytes) -> Optional[dict]:
        try:
            with tarfile.open(fileobj=io.BytesIO(blob), mode="r:gz") as tf:
                for m in tf.getmembers():
                    if os.path.basename(m.name) == "manifest.json":
                        f = tf.extractfile(m)
                        if f is not None:
                            return json.load(f)
        except (tarfile.TarError, ValueError, json.JSONDecodeError):
            pass
        return None

    def list_artifacts(self) -> list:
        out = []
        base = os.path.join(self.root, "artifacts")
        for digest in sorted(os.listdir(base)):
            meta_path = os.path.join(base, digest, "meta.json")
            if os.path.exists(meta_path):
                with open(meta_path) as f:
                    out.append(json.load(f))
        return out

    def get_artifact(self, digest: str) -> Optional[str]:
        path = os.path.join(self._artifact_path(digest), "bundle.tar.gz")
        return path if os.path.exists(path) else None

    def delete_artifact(self, digest: str) -> bool:
        import shutil

        path = self._artifact_path(digest)
        if not os.path.isdir(path):
            return False
        shutil.rmtree(path)
        return True

    # -- deployments ---------------------------------------------------------

    def _deployment_path(self, name: str) -> str:
        safe = name.replace("/", "_")
        return os.path.join(self.root, "deployments", f"{safe}.json")

    def put_deployment(self, name: str, artifact: str, config: dict) -> dict:
        if self.get_artifact(artifact) is None:
            raise web.HTTPNotFound(text=f"artifact {artifact} not found")
        dep = {
            "name": name,
            "artifact": artifact,
            "config": config,
            "updated_at": time.time(),
        }
        # tmp + rename (same pattern as put_artifact): a crash mid-write must
        # not leave a truncated JSON that turns every list/get into a 500
        path = self._deployment_path(name)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(dep, f)
        os.replace(tmp, path)
        return dep

    def list_deployments(self) -> list:
        base = os.path.join(self.root, "deployments")
        out = []
        for fn in sorted(os.listdir(base)):
            if not fn.endswith(".json"):
                continue  # skip orphaned .tmp files from a crashed writer
            try:
                with open(os.path.join(base, fn)) as f:
                    out.append(json.load(f))
            except (json.JSONDecodeError, OSError):
                continue  # a corrupt entry must not take the listing down
        return out

    def get_deployment(self, name: str) -> Optional[dict]:
        path = self._deployment_path(name)
        if not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                return json.load(f)
        except (json.JSONDecodeError, OSError):
            return None  # corrupt legacy entry → 404, consistent with listing

    def delete_deployment(self, name: str) -> bool:
        path = self._deployment_path(name)
        if not os.path.exists(path):
            return False
        os.unlink(path)
        return True


def build_app(store: ArtifactStore) -> web.Application:
    app = web.Application(client_max_size=MAX_BUNDLE_BYTES)

    async def post_artifact(request: web.Request) -> web.Response:
        name = request.headers.get("X-Bundle-Name", "bundle")
        blob = await request.read()
        if not blob:
            raise web.HTTPBadRequest(text="empty body")
        # hashing + tar parsing + writing a bundle of up to 512MB must not
        # stall the event loop (health probes, concurrent fetches)
        meta = await asyncio.to_thread(store.put_artifact, name, blob)
        return web.json_response(meta, status=201)

    async def list_artifacts(_request: web.Request) -> web.Response:
        return web.json_response({"artifacts": store.list_artifacts()})

    async def get_artifact(request: web.Request) -> web.StreamResponse:
        path = store.get_artifact(request.match_info["digest"])
        if path is None:
            raise web.HTTPNotFound()
        return web.FileResponse(path)

    async def delete_artifact(request: web.Request) -> web.Response:
        if not store.delete_artifact(request.match_info["digest"]):
            raise web.HTTPNotFound()
        return web.json_response({"deleted": True})

    async def post_deployment(request: web.Request) -> web.Response:
        try:
            body = await request.json()
            name, artifact = body["name"], body["artifact"]
        except (ValueError, KeyError):
            raise web.HTTPBadRequest(text="need {name, artifact, config?}")
        dep = store.put_deployment(name, artifact, body.get("config") or {})
        return web.json_response(dep, status=201)

    async def list_deployments(_request: web.Request) -> web.Response:
        return web.json_response({"deployments": store.list_deployments()})

    async def get_deployment(request: web.Request) -> web.Response:
        dep = store.get_deployment(request.match_info["name"])
        if dep is None:
            raise web.HTTPNotFound()
        return web.json_response(dep)

    async def delete_deployment(request: web.Request) -> web.Response:
        if not store.delete_deployment(request.match_info["name"]):
            raise web.HTTPNotFound()
        return web.json_response({"deleted": True})

    async def health(_request: web.Request) -> web.Response:
        return web.json_response({"status": "ok"})

    app.router.add_post("/v1/artifacts", post_artifact)
    app.router.add_get("/v1/artifacts", list_artifacts)
    app.router.add_get("/v1/artifacts/{digest}", get_artifact)
    app.router.add_delete("/v1/artifacts/{digest}", delete_artifact)
    app.router.add_post("/v1/deployments", post_deployment)
    app.router.add_get("/v1/deployments", list_deployments)
    app.router.add_get("/v1/deployments/{name}", get_deployment)
    app.router.add_delete("/v1/deployments/{name}", delete_deployment)
    app.router.add_get("/health", health)
    return app


async def serve(root: str, host: str, port: int):
    app = build_app(ArtifactStore(root))
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    actual = runner.addresses[0][1] if runner.addresses else port
    logger.info("artifact store on %s:%s (root %s)", host, actual, root)
    return runner


def main() -> None:
    ap = argparse.ArgumentParser(description="dynamo_tpu artifact store")
    ap.add_argument("--root", default="./dynamo_artifacts")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=7411)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    async def run():
        await serve(args.root, args.host, args.port)
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":
    main()
