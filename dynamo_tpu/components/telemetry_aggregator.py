"""Cluster telemetry aggregator: worker streams → rollups, SLOs, burn rates.

``components/metrics.py`` re-exports each worker's latest snapshot; this
component *consumes* the same streams and turns them into cluster-level
answers — the telemetry→decision bridge ROADMAP item 4's planner needs:

- **ingest** — the ``kv_metrics`` event-plane stream every worker already
  publishes (``attach_kv_publishing``): capacity counters, health state,
  the PR5 ``phase_latency`` summaries (now carrying raw bucket counts),
  and the new engine perf gauges. Cumulative counters and histogram
  snapshots are *differenced* per worker so restarts and resets never
  produce negative rates.
- **rollups** — per-model cluster capacity headroom (free slots / free KV
  blocks over totals), worker count by health, worst/median worker by load
  score, fleet decode tokens/s.
- **SLOs** — the differenced TTFT/ITL bucket deltas, request outcomes, and
  health heartbeats feed a :class:`~dynamo_tpu.runtime.telemetry.MetricStore`
  per model; a :class:`~dynamo_tpu.runtime.telemetry.SloEngine` evaluates
  the catalog with multi-window burn rates (docs/observability.md).

Surfaces: the ``telemetry_dump`` RPC verb (the aggregator registers a
``{ns}.telemetry.status`` endpoint so ``llmctl slo status`` / ``llmctl
cluster status`` can find it through ordinary discovery), a ``/metrics``
cluster section, and ``GET /debug/slo`` when embedded in a frontend.

Run:  python -m dynamo_tpu.components.telemetry_aggregator --namespace dynamo --port 9092
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import statistics
import time
from typing import Callable, Dict, List, Optional, Tuple

from dynamo_tpu.kv_router.protocols import ForwardPassMetrics
from dynamo_tpu.runtime import telemetry
from dynamo_tpu.runtime.telemetry import (
    MetricStore,
    SloEngine,
    TelemetryPolicy,
)

logger = logging.getLogger(__name__)

# phase → SLO series fed from worker phase_latency summaries. Bounds come
# from the tracing plane's histogram (seconds), converted to the telemetry
# store's native ms.
_PHASE_SERIES = {"ttft": "ttft_ms", "inter_token": "itl_ms"}

# cluster exposition catalog (metric-name-valid lint checks *GAUGES tables)
CLUSTER_GAUGES = [
    ("workers", "Workers currently reporting metrics"),
    ("workers_unhealthy", "Workers self-reporting unhealthy"),
    ("slots_total", "Decode slots across the fleet"),
    ("slots_free", "Free decode slots across the fleet"),
    ("kv_blocks_total", "KV pool blocks across the fleet"),
    ("kv_blocks_free", "Free KV pool blocks across the fleet"),
    ("headroom_frac", "min(free slots, free KV) fraction of fleet capacity"),
    ("queue_depth", "Requests waiting beyond engine slots (fleet sum)"),
    ("decode_tokens_per_s", "Fleet decode throughput (sum of worker EMAs)"),
    # speculative decoding (PR7): fleet draft counters + acceptance rate
    # recomputed from the summed counters (not a mean of worker EMAs)
    ("spec_drafted_tokens", "Draft tokens handed to verify dispatches (fleet sum)"),
    ("spec_accepted_tokens", "Draft tokens accepted (fleet sum)"),
    ("spec_accept_rate", "Fleet speculative acceptance rate (accepted/drafted)"),
    # mid-stream resume (docs/resilience.md): fleet recovery counters
    ("resume_total", "Streams resumed on another worker mid-decode (fleet sum)"),
    ("resume_failed_total", "Resumable streams that still failed in-band (fleet sum)"),
    # live in-flight migration (docs/resilience.md §Live migration)
    ("migrations_total", "Streams live-migrated on drain (fleet sum)"),
    ("migrations_failed_total", "Drain migrations degraded to resume (fleet sum)"),
    ("migrate_kv_blocks_moved_total", "KV blocks moved by live migration (fleet sum)"),
    # control-plane blackout tolerance (docs/resilience.md): workers whose
    # own view of the statestore/bus planes is stale or disconnected, and
    # the fleet's cumulative outage-buffer drops
    ("control_plane_impaired", "Workers reporting a stale/disconnected control plane"),
    ("bus_dropped_events", "Events dropped from control-plane outage buffers (fleet sum)"),
    # silent-corruption defense (docs/resilience.md §Silent corruption):
    # fleet integrity trip counters + workers currently quarantined
    ("kv_integrity_failures_total", "KV blocks that failed content checksums (fleet sum)"),
    ("watchdog_trips_total", "Lanes ended by the output watchdog (fleet sum)"),
    ("workers_quarantined", "Workers quarantined by the integrity plane"),
    # fail-slow defense (docs/resilience.md §Fail-slow): workers currently
    # under a differential straggler verdict (suspect or confirmed)
    ("workers_suspect", "Workers under a fail-slow suspect/confirmed verdict"),
    # performance attribution plane (docs/observability.md §Profiling):
    # fleet WORST dispatch split / idle fraction (p95s are not summable —
    # the slowest worker is the one to profile) + summed jit recompiles
    ("dispatch_device_us_p95", "Worst per-worker decode dispatch device-time p95 (us)"),
    ("dispatch_host_overhead_us_p95", "Worst per-worker decode dispatch host-overhead p95 (us)"),
    ("device_idle_frac", "Worst per-worker device idle fraction between dispatches"),
    ("jit_recompiles_total", "Jitted step-function compilations since boot (fleet sum)"),
    ("worst_worker_load", "Highest per-worker load score"),
    ("median_worker_load", "Median per-worker load score"),
]

# per-tenant cluster gauges (docs/qos.md): summed from worker `tenants`
# dicts; labels {namespace, model, tenant}. Rendered only when at least
# one worker reports tenants (single-tenant fleets emit no lines).
TENANT_GAUGES = [
    ("active_slots", "Decode slots this tenant occupies (fleet sum)"),
    ("queue_depth", "Requests this tenant has queued/awaiting (fleet sum)"),
    ("kv_blocks", "KV pool blocks this tenant holds (fleet sum)"),
    ("admitted_total", "Requests admitted past the tenant rate gate (cumulative)"),
    ("rate_limited_total", "Requests shed by the tenant rate gate (cumulative)"),
    ("shed_share", "rate_limited / offered over the fast window (current throttling)"),
    ("shed_share_cumulative", "rate_limited / offered since worker start"),
]


def _phase_bounds_ms() -> Tuple[float, ...]:
    from dynamo_tpu.runtime.tracing import PHASE_BUCKETS

    return tuple(b * 1e3 for b in PHASE_BUCKETS)


class _WorkerView:
    """Latest snapshot + the cumulative baselines used for differencing."""

    __slots__ = (
        "metrics", "last_seen", "model",
        "phase_counts", "phase_sums", "counters",
    )

    def __init__(self) -> None:
        self.metrics: Optional[ForwardPassMetrics] = None
        self.last_seen = 0.0
        self.model = ""
        # phase → cumulative per-bound counts at last ingest
        self.phase_counts: Dict[str, List[int]] = {}
        self.phase_sums: Dict[str, float] = {}
        # counter name → cumulative value at last ingest
        self.counters: Dict[str, float] = {}


def _decumulate(cum: List[int]) -> List[int]:
    """Prometheus-style cumulative bucket counts → per-bound counts."""
    out = []
    prev = 0
    for c in cum:
        out.append(max(int(c) - prev, 0))
        prev = int(c)
    return out


class ClusterTelemetry:
    """The aggregation core (transport-free, deterministic under test)."""

    def __init__(
        self,
        namespace: str,
        policy: Optional[TelemetryPolicy] = None,
        expiry: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.namespace = namespace
        self.policy = policy or TelemetryPolicy.from_env()
        self.expiry = expiry
        self.clock = clock
        from dynamo_tpu.runtime.telemetry import declare_standard_series

        # latency bounds follow the tracing plane's histogram (ms): worker
        # snapshots diff straight into these series
        self.store = declare_standard_series(
            MetricStore(self.policy, clock=clock),
            latency_bounds=_phase_bounds_ms(),
        )
        # per-tenant rate-gate outcomes as WINDOWED counters (docs/qos.md):
        # the rollup's shed_share reads the fast window from these, so
        # `llmctl tenant status` exit-2 reflects *current* throttling —
        # a tenant abused an hour ago but quiet now must read 0, not its
        # lifetime average
        from dynamo_tpu.runtime.telemetry import COUNTER

        self.store.declare("tenant_admitted", COUNTER)
        self.store.declare("tenant_rate_limited", COUNTER)
        self.slo_engine = SloEngine(self.store, self.policy, clock=clock)
        # fail-slow defense (docs/resilience.md §Fail-slow): when
        # run_telemetry_aggregator arms DYN_TPU_STRAGGLER it installs a
        # StragglerArbiter here; ingest() then feeds it each worker's
        # normalized dispatch EWMA + sample counter so the arbiter can make
        # fleet-relative verdicts. None ⇒ feature off, zero overhead.
        self.straggler_arbiter = None
        self._workers: Dict[str, _WorkerView] = {}
        # (model, tenant) pairs with at least one post-baseline diff: until
        # then the windowed series has seen nothing and the cumulative
        # share is the only honest answer (a brand-new aggregator meeting
        # an old fleet must not report every tenant as unthrottled)
        self._tenant_windowed: set = set()

    # -- ingest --------------------------------------------------------------

    def ingest(self, worker_id: str, metrics: ForwardPassMetrics) -> None:
        now = self.clock()
        view = self._workers.get(worker_id)
        if view is None:
            view = self._workers[worker_id] = _WorkerView()
        view.metrics = metrics
        view.last_seen = now
        model = getattr(metrics, "model", None) or view.model or "unknown"
        view.model = model

        # availability: one 0/1 sample per heartbeat per worker, pooled into
        # the model's gauge series — the window average IS the healthy share
        available = 1.0 if (
            getattr(metrics, "health_state", "healthy")
            not in ("unhealthy", "quarantined")
            and not getattr(metrics, "draining", 0)
        ) else 0.0
        self.store.series("worker_available", model=model).set(available, now)

        # fail-slow: feed the arbiter only workers with a live detector
        # (samples_total > 0) — a DYN_TPU_STRAGGLER=0 worker publishes
        # zeros and must neither be judged nor count toward min_peers
        if self.straggler_arbiter is not None:
            samples = int(getattr(metrics, "straggler_samples_total", 0) or 0)
            if samples > 0:
                self.straggler_arbiter.observe(
                    worker_id, model,
                    float(getattr(metrics, "dispatch_us_per_token_ewma", 0.0) or 0.0),
                    samples, now=now,
                )

        self._ingest_phases(view, metrics, model, now)
        self._ingest_counters(view, metrics, model, now)
        self._ingest_tenants(view, metrics, model, now)

    def _ingest_tenants(
        self, view: _WorkerView, metrics: ForwardPassMetrics,
        model: str, now: float,
    ) -> None:
        """Diff each worker's cumulative per-tenant rate-gate counters into
        windowed series (same baseline/restart discipline as
        :meth:`_ingest_counters`): the rollup's *current* shed share reads
        these instead of lifetime totals."""
        wt = getattr(metrics, "tenants", None)
        if not isinstance(wt, dict):
            return
        for tname, tview in wt.items():
            if not isinstance(tview, dict):
                continue
            for src, series_name in (
                ("admitted", "tenant_admitted"),
                ("rate_limited", "tenant_rate_limited"),
            ):
                try:
                    cur = float(tview.get(src, 0) or 0)
                except (TypeError, ValueError):
                    continue
                key = f"tenant:{tname}:{src}"
                prev = view.counters.get(key)
                if prev is None:
                    view.counters[key] = cur
                    continue
                if cur < prev:  # worker restart: fresh counters = new events
                    prev = 0.0
                d = cur - prev
                if d > 0:
                    self.store.series(
                        series_name, model=model, tenant=str(tname)
                    ).inc(d, now)
                view.counters[key] = cur
                # a second sighting — even a zero delta — means the window
                # is live for this tenant: quiet IS "not currently throttled"
                self._tenant_windowed.add((model, str(tname)))

    def _ingest_phases(
        self, view: _WorkerView, metrics: ForwardPassMetrics,
        model: str, now: float,
    ) -> None:
        phases = getattr(metrics, "phase_latency", None)
        if not isinstance(phases, dict):
            return
        for phase, series_name in _PHASE_SERIES.items():
            stats = phases.get(phase)
            if not isinstance(stats, dict):
                continue
            cum = stats.get("buckets")
            if not isinstance(cum, list):
                continue  # pre-PR6 worker: summary without raw buckets
            counts = _decumulate(cum)
            series = self.store.series(series_name, model=model)
            if len(counts) != len(series.bounds):
                continue  # bounds drift across versions: skip, never corrupt
            prev = view.phase_counts.get(phase)
            sum_ms = float(stats.get("sum_s", 0.0)) * 1e3
            if prev is None:
                # first sight: baseline only, observe nothing — the
                # snapshot may hold hours of already-lived history (a new
                # aggregator against an old fleet, or a worker returning
                # after an expiry gap), and dumping it into the current
                # ring bucket would double-count it at "now" and fire a
                # false page
                view.phase_counts[phase] = counts
                view.phase_sums[phase] = sum_ms
                continue
            if len(prev) != len(counts) or any(
                c < p for c, p in zip(counts, prev)
            ):
                # reset (worker restart / tracing.configure): the fresh
                # process's counts ARE new samples — and small, one
                # process-lifetime of a just-restarted worker
                prev = [0] * len(counts)
                view.phase_sums[phase] = 0.0
            delta = [c - p for c, p in zip(counts, prev)]
            d_sum = max(sum_ms - view.phase_sums.get(phase, 0.0), 0.0)
            if any(delta):
                series.observe_bucketed(delta, d_sum, now)
            view.phase_counts[phase] = counts
            view.phase_sums[phase] = sum_ms

    def _ingest_counters(
        self, view: _WorkerView, metrics: ForwardPassMetrics,
        model: str, now: float,
    ) -> None:
        for attr, series_name in (
            ("requests_total", "requests_total"),
            ("requests_errored", "requests_errored"),
            ("shed_requests", "requests_shed"),
        ):
            cur = float(getattr(metrics, attr, 0) or 0)
            prev = view.counters.get(attr)
            if prev is None:
                # first sight: baseline only (see _ingest_phases)
                view.counters[attr] = cur
                continue
            if cur < prev:  # worker restart: fresh counters are new events
                prev = 0.0
            d = cur - prev
            if d > 0:
                self.store.series(series_name, model=model).inc(d, now)
            view.counters[attr] = cur

    # -- rollups -------------------------------------------------------------

    def live_workers(self) -> Dict[str, _WorkerView]:
        """Workers fresh enough for the capacity rollup. Views are only
        DELETED on a much longer horizon: a worker quiet past ``expiry``
        (bus hiccup, GC pause) must drop out of the rollup but keep its
        diff baselines — deleting them would make its next publish look
        like first sight and silently skip (or, before the baseline-only
        fix, double-count) its history."""
        now = self.clock()
        cutoff = now - self.expiry
        drop = now - max(self.expiry * 20, 600.0)
        self._workers = {
            w: v for w, v in self._workers.items() if v.last_seen >= drop
        }
        return {
            w: v for w, v in self._workers.items() if v.last_seen >= cutoff
        }

    @staticmethod
    def _load_score(m: ForwardPassMetrics) -> float:
        """Same shape as LoadSnapshot.utilization(): slot + queue + KV
        pressure; higher = busier."""
        score = 0.0
        slots = max(int(m.request_total_slots or 0), 0)
        if slots > 0:
            score += m.request_active_slots / slots
            score += m.num_requests_waiting / slots
        blocks = max(int(m.kv_total_blocks or 0), 0)
        if blocks > 0:
            score += m.kv_active_blocks / blocks
        return round(score, 4)

    def rollup(self) -> dict:
        """Instantaneous cluster capacity/health view, per model + total.

        Per model: fleet capacity sums, aggregate ``queue_depth`` (requests
        waiting beyond engine slots), a ``pools`` breakdown keyed by worker
        role (``decode`` | ``prefill`` | ``frontend``; pre-planner workers
        without a role bucket as ``decode``), and a bounded
        ``unhealthy_worker_ids`` list — together the observation the planner
        (``components/planner.py``) resizes pools and drains workers from.
        """
        live = self.live_workers()
        models: Dict[str, dict] = {}
        scores: List[Tuple[str, float]] = []
        for wid, view in sorted(live.items()):
            m = view.metrics
            if m is None:
                continue
            entry = models.setdefault(view.model, {
                "workers": 0, "workers_unhealthy": 0,
                "slots_total": 0, "slots_free": 0,
                "kv_blocks_total": 0, "kv_blocks_free": 0,
                "queue_depth": 0,
                "decode_tokens_per_s": 0.0,
                "spec_drafted_tokens": 0, "spec_accepted_tokens": 0,
                "spec_accept_rate": 0.0,
                "resume_total": 0, "resume_failed_total": 0,
                "migrations_total": 0, "migrations_failed_total": 0,
                "migrate_kv_blocks_moved_total": 0,
                "kv_integrity_failures_total": 0,
                "watchdog_trips_total": 0,
                "workers_quarantined": 0,
                "quarantined_worker_ids": [],
                "workers_suspect": 0,
                "straggler_worker_ids": [],
                "dispatch_device_us_p95": 0.0,
                "dispatch_host_overhead_us_p95": 0.0,
                "device_idle_frac": 0.0,
                "jit_recompiles_total": 0,
                "control_plane_impaired": 0,
                "bus_dropped_events": 0,
                "control_plane": {
                    "connected": 0, "stale": 0, "disconnected": 0,
                    "impaired_worker_ids": [],
                },
                "pools": {},
                "tenants": {},
                "unhealthy_worker_ids": [],
                "draining_workers": {},
            })
            entry["workers"] += 1
            unhealthy = getattr(m, "health_state", "healthy") == "unhealthy"
            if unhealthy:
                entry["workers_unhealthy"] += 1
                # bounded: the planner needs names to drain, but a mass
                # outage must not balloon the rollup payload
                if len(entry["unhealthy_worker_ids"]) < 16:
                    entry["unhealthy_worker_ids"].append(wid)
            # quarantine (docs/resilience.md §Silent corruption): counted
            # and named separately — the planner drains these too, but a
            # quarantined worker must never auto-undrain (recovery requires
            # state EXACTLY healthy, which quarantine never reports)
            if getattr(m, "health_state", "healthy") == "quarantined":
                entry["workers_quarantined"] += 1
                if len(entry["quarantined_worker_ids"]) < 16:
                    entry["quarantined_worker_ids"].append(wid)
            # fail-slow (docs/resilience.md §Fail-slow): counted from the
            # worker-ECHOED verdict, not the arbiter's local state — the
            # rollup then reflects the closed loop (arbiter → store key →
            # worker latch → heartbeat), and mock workers can drill the
            # rendering without a live arbiter
            if getattr(m, "straggler_state", "ok") in ("suspect", "confirmed"):
                entry["workers_suspect"] += 1
                if len(entry["straggler_worker_ids"]) < 16:
                    entry["straggler_worker_ids"].append(wid)
            slots_total = int(m.request_total_slots or 0)
            slots_free = max(
                slots_total - int(m.request_active_slots or 0), 0
            )
            waiting = max(int(m.num_requests_waiting or 0), 0)
            entry["slots_total"] += slots_total
            entry["slots_free"] += slots_free
            entry["queue_depth"] += waiting
            entry["kv_blocks_total"] += int(m.kv_total_blocks or 0)
            entry["kv_blocks_free"] += max(
                int(m.kv_total_blocks or 0) - int(m.kv_active_blocks or 0), 0
            )
            entry["decode_tokens_per_s"] = round(
                entry["decode_tokens_per_s"]
                + float(getattr(m, "decode_tokens_per_s", 0.0) or 0.0), 3,
            )
            # speculation: cumulative counters sum; the fleet acceptance
            # rate is recomputed below from the summed counters (a mean of
            # per-worker EMAs would overweight idle workers)
            entry["spec_drafted_tokens"] += int(
                getattr(m, "spec_drafted_tokens", 0) or 0
            )
            entry["spec_accepted_tokens"] += int(
                getattr(m, "spec_accepted_tokens", 0) or 0
            )
            # mid-stream resume: fleet recovery counters (cumulative sums —
            # like the spec counters, rates come from diffing scrapes)
            entry["resume_total"] += int(getattr(m, "resume_total", 0) or 0)
            entry["resume_failed_total"] += int(
                getattr(m, "resume_failed_total", 0) or 0
            )
            # live migration: fleet drain-migration counters (same
            # cumulative-sum discipline as the resume counters)
            entry["migrations_total"] += int(
                getattr(m, "migrations_total", 0) or 0
            )
            entry["migrations_failed_total"] += int(
                getattr(m, "migrations_failed_total", 0) or 0
            )
            entry["migrate_kv_blocks_moved_total"] += int(
                getattr(m, "migrate_kv_blocks_moved_total", 0) or 0
            )
            # integrity plane: cumulative trip counters (same cumulative-
            # sum discipline as the resume/migration counters)
            entry["kv_integrity_failures_total"] += int(
                getattr(m, "kv_integrity_failures_total", 0) or 0
            )
            entry["watchdog_trips_total"] += int(
                getattr(m, "watchdog_trips_total", 0) or 0
            )
            # profiling plane: worst-worker p95s / idle fraction (max, not
            # sum — see the CLUSTER_GAUGES note) + summed jit recompiles
            entry["dispatch_device_us_p95"] = max(
                entry["dispatch_device_us_p95"],
                float(getattr(m, "dispatch_device_us_p95", 0.0) or 0.0),
            )
            entry["dispatch_host_overhead_us_p95"] = max(
                entry["dispatch_host_overhead_us_p95"],
                float(getattr(m, "dispatch_host_overhead_us_p95", 0.0) or 0.0),
            )
            entry["device_idle_frac"] = max(
                entry["device_idle_frac"],
                float(getattr(m, "device_idle_frac", 0.0) or 0.0),
            )
            entry["jit_recompiles_total"] += int(
                getattr(m, "jit_recompiles", 0) or 0
            )
            # control-plane view per worker: count by state, name the
            # impaired ones (bounded like unhealthy_worker_ids) so `llmctl
            # control-plane status` can say WHO is cut off, and sum the
            # outage-buffer drops
            cp_state = getattr(m, "control_plane_state", "") or "connected"
            if cp_state not in ("connected", "stale", "disconnected"):
                cp_state = "disconnected"  # unknown future state ≠ fine
            entry["control_plane"][cp_state] += 1
            if cp_state != "connected":
                entry["control_plane_impaired"] += 1
                if len(entry["control_plane"]["impaired_worker_ids"]) < 16:
                    entry["control_plane"]["impaired_worker_ids"].append(wid)
            entry["bus_dropped_events"] += int(
                getattr(m, "bus_dropped_events", 0) or 0
            )
            # pool-role breakdown: what the planner actually resizes
            role = getattr(m, "role", "") or "decode"
            pool = entry["pools"].setdefault(role, {
                "workers": 0, "workers_unhealthy": 0,
                "slots_total": 0, "slots_free": 0, "queue_depth": 0,
                "kv_blocks_total": 0, "kv_blocks_free": 0,
            })
            pool["workers"] += 1
            if unhealthy:
                pool["workers_unhealthy"] += 1
            pool["slots_total"] += slots_total
            pool["slots_free"] += slots_free
            pool["queue_depth"] += waiting
            pool["kv_blocks_total"] += int(m.kv_total_blocks or 0)
            pool["kv_blocks_free"] += max(
                int(m.kv_total_blocks or 0) - int(m.kv_active_blocks or 0), 0
            )
            # per-tenant QoS rollup (docs/qos.md): sum the numeric fields
            # of each worker's `tenants` dict; the class label keeps the
            # first sighting (it is policy, identical across the fleet)
            wt = getattr(m, "tenants", None)
            if isinstance(wt, dict):
                for tname, tview in wt.items():
                    if not isinstance(tview, dict):
                        continue
                    te = entry["tenants"].setdefault(str(tname), {
                        "class": str(tview.get("class", "")),
                        "active_slots": 0, "queue_depth": 0, "kv_blocks": 0,
                        "admitted_total": 0, "rate_limited_total": 0,
                    })
                    for src, dst in (
                        ("active_slots", "active_slots"),
                        ("queue_depth", "queue_depth"),
                        ("kv_blocks", "kv_blocks"),
                        ("admitted", "admitted_total"),
                        ("rate_limited", "rate_limited_total"),
                    ):
                        try:
                            te[dst] += int(tview.get(src, 0) or 0)
                        except (TypeError, ValueError):
                            pass
            # positive-evidence map for the planner's undrain path: a
            # drained worker that crashed simply STOPS publishing — its
            # absence here must read as "unknown", never as "recovered"
            if getattr(m, "draining", 0) and len(
                entry["draining_workers"]
            ) < 32:
                entry["draining_workers"][wid] = getattr(
                    m, "health_state", "healthy"
                )
            scores.append((wid, self._load_score(m)))
        for entry in models.values():
            slot_frac = (
                entry["slots_free"] / entry["slots_total"]
                if entry["slots_total"] else 0.0
            )
            kv_frac = (
                entry["kv_blocks_free"] / entry["kv_blocks_total"]
                if entry["kv_blocks_total"] else 0.0
            )
            # headroom is the BINDING constraint: whichever of slots or KV
            # runs out first caps admission (runtime/admission.py)
            entry["headroom_frac"] = round(min(slot_frac, kv_frac), 4)
            for pool in entry["pools"].values():
                p_slot = (
                    pool["slots_free"] / pool["slots_total"]
                    if pool["slots_total"] else 0.0
                )
                # same binding-constraint rule as the model level; pools
                # with no KV pool at all (frontends) are slot-bound only
                p_kv = (
                    pool["kv_blocks_free"] / pool["kv_blocks_total"]
                    if pool["kv_blocks_total"] else p_slot
                )
                pool["headroom_frac"] = round(min(p_slot, p_kv), 4)
            if entry["spec_drafted_tokens"]:
                entry["spec_accept_rate"] = round(
                    entry["spec_accepted_tokens"] / entry["spec_drafted_tokens"],
                    4,
                )
        # tenant shed share is computed per model AFTER the worker sweep so
        # the windowed query runs once per (model, tenant), not per worker
        window = self.policy.fast_window
        for model, entry in models.items():
            for tname, te in entry["tenants"].items():
                seen = te["admitted_total"] + te["rate_limited_total"]
                # lifetime share kept for dashboards/history...
                te["shed_share_cumulative"] = round(
                    te["rate_limited_total"] / seen, 4
                ) if seen else 0.0
                # ...but `shed_share` — what llmctl tenant status exit-2
                # keys on — is the FAST-WINDOW share: a tenant throttled an
                # hour ago and quiet now reads 0.0, a tenant being
                # throttled right now reads ~1.0. Until the first
                # post-baseline diff the cumulative share stands in (a new
                # aggregator has no window yet).
                if (model, tname) in self._tenant_windowed:
                    lim = self.store.series(
                        "tenant_rate_limited", model=model, tenant=tname
                    ).window_sum(window)
                    adm = self.store.series(
                        "tenant_admitted", model=model, tenant=tname
                    ).window_sum(window)
                    offered = adm + lim
                    te["shed_share"] = round(
                        lim / offered, 4
                    ) if offered else 0.0
                    te["shed_share_window_s"] = round(window, 3)
                else:
                    te["shed_share"] = te["shed_share_cumulative"]
        worst = max(scores, key=lambda t: t[1]) if scores else None
        med = (
            round(statistics.median(s for _, s in scores), 4) if scores else None
        )
        return {
            "namespace": self.namespace,
            "workers": len(live),
            "models": models,
            "worst_worker": (
                {"worker_id": worst[0], "load": worst[1]} if worst else None
            ),
            "median_worker_load": med,
        }

    def slo_report(self) -> List[dict]:
        return self.slo_engine.report()

    def dump(self) -> dict:
        """The ``telemetry_dump`` / ``/debug/slo`` cluster payload."""
        return {
            "rollup": self.rollup(),
            "slo": self.slo_report(),
            "windows": {
                "fast_s": self.policy.fast_window,
                "mid_s": self.policy.mid_window,
                "slow_s": self.policy.slow_window,
                "burn_fast": self.policy.burn_fast,
                "burn_slow": self.policy.burn_slow,
            },
        }

    def render_prometheus(self, prefix: str = "dynamo_cluster") -> str:
        """The cluster /metrics section: capacity + SLO compliance/burn."""
        from dynamo_tpu.llm.http.metrics import fmt_labels

        roll = self.rollup()
        lines: List[str] = []
        per_model_keys = {k for k, _ in CLUSTER_GAUGES} - {
            "worst_worker_load", "median_worker_load",
        }
        for name, help_text in CLUSTER_GAUGES:
            full = f"{prefix}_{name}"
            lines.append(f"# HELP {full} {help_text}")
            lines.append(f"# TYPE {full} gauge")
            if name == "worst_worker_load":
                w = roll.get("worst_worker")
                if w:
                    lbl = fmt_labels({
                        "namespace": self.namespace, "worker": w["worker_id"],
                    })
                    lines.append(f"{full}{lbl} {w['load']}")
                continue
            if name == "median_worker_load":
                med = roll.get("median_worker_load")
                if med is not None:
                    lbl = fmt_labels({"namespace": self.namespace})
                    lines.append(f"{full}{lbl} {med}")
                continue
            if name == "workers":
                lbl = fmt_labels({"namespace": self.namespace})
                lines.append(f"{full}{lbl} {roll['workers']}")
                continue
            if name in per_model_keys:
                for model, entry in sorted(roll["models"].items()):
                    if name not in entry:
                        continue
                    lbl = fmt_labels({
                        "namespace": self.namespace, "model": model,
                    })
                    lines.append(f"{full}{lbl} {entry[name]}")
        # per-tenant QoS gauges (docs/qos.md) — emitted only when some
        # worker reports tenants, so single-tenant fleets add zero lines
        if any(e.get("tenants") for e in roll["models"].values()):
            for name, help_text in TENANT_GAUGES:
                full = f"dynamo_tenant_{name}"
                lines.append(f"# HELP {full} {help_text}")
                lines.append(f"# TYPE {full} gauge")
                for model, entry in sorted(roll["models"].items()):
                    for tenant, te in sorted(
                        (entry.get("tenants") or {}).items()
                    ):
                        lbl = fmt_labels({
                            "namespace": self.namespace, "model": model,
                            "tenant": tenant,
                        })
                        lines.append(f"{full}{lbl} {te.get(name, 0)}")
        # SLO state: compliance ratio over the slow window + fast burn rate
        comp = f"{prefix}_slo_compliance"
        burn = f"{prefix}_slo_burn_rate"
        alert = f"{prefix}_slo_alert"
        lines.append(f"# HELP {comp} Good-event ratio over the slow window")
        lines.append(f"# TYPE {comp} gauge")
        burn_lines = [
            f"# HELP {burn} Error-budget burn rate over the fast window",
            f"# TYPE {burn} gauge",
        ]
        alert_lines = [
            f"# HELP {alert} 0=ok 1=burning(ticket) 2=alert(page)",
            f"# TYPE {alert} gauge",
        ]
        for status in self.slo_report():
            lbl = fmt_labels(dict(
                status.get("labels", {}),
                namespace=self.namespace, slo=status["slo"],
            ))
            ratio = status.get("ratio_slow")
            if ratio is not None:
                lines.append(f"{comp}{lbl} {ratio:.6f}")
            burn_lines.append(f"{burn}{lbl} {status.get('burn_fast', 0.0)}")
            state_val = {"ok": 0, "burning": 1, "alert": 2}.get(
                status.get("state", "ok"), 2
            )
            alert_lines.append(f"{alert}{lbl} {state_val}")
        lines.extend(burn_lines)
        lines.extend(alert_lines)
        return "\n".join(lines) + "\n"


async def run_telemetry_aggregator(
    drt,
    namespace: str,
    port: int = 0,
    host: str = "0.0.0.0",
    expiry: float = 30.0,
    register: bool = True,
    ready: Optional[asyncio.Event] = None,
    bound_port: Optional[List[int]] = None,
) -> None:
    """Consume the worker metrics stream, serve the cluster view, and (by
    default) register a ``{ns}.telemetry.status`` endpoint so ``llmctl slo
    status`` finds this aggregator through ordinary discovery. The
    aggregator also installs itself as the process-global cluster
    (``telemetry.set_cluster``) so the ``telemetry_dump`` RPC verb and any
    co-hosted frontend's ``/debug/slo`` include it."""
    from aiohttp import web

    from dynamo_tpu.runtime.annotated import Annotated
    from dynamo_tpu.runtime.distributed import (
        KV_METRICS_SUBJECT,
        resubscribe_forever,
    )
    from dynamo_tpu.runtime.engine import AsyncEngine, Context

    cluster = ClusterTelemetry(namespace, expiry=expiry)
    telemetry.set_cluster(cluster)
    ns = drt.namespace(namespace)
    consumer = asyncio.create_task(resubscribe_forever(
        ns, KV_METRICS_SUBJECT,
        lambda d: cluster.ingest(
            d["worker_id"], ForwardPassMetrics.from_dict(d["metrics"])
        ),
    ))

    # fail-slow arbiter (docs/resilience.md §Fail-slow): with
    # DYN_TPU_STRAGGLER armed, judge each worker's dispatch EWMA against
    # the fleet median once per detection window and publish non-ok
    # verdicts as leased statestore keys ({ns}/straggler/{worker_id} =
    # b"suspect"|b"confirmed"). Workers watch the prefix and latch the
    # verdict; the LEASE is the failure-domain boundary — an aggregator
    # crash expires its verdicts instead of wedging the fleet demoted.
    from dynamo_tpu.runtime import straggler as straggler_mod

    straggler_task: Optional[asyncio.Task] = None
    pol = straggler_mod.maybe_from_env()
    if pol is not None:
        arbiter = straggler_mod.StragglerArbiter(pol)
        cluster.straggler_arbiter = arbiter

        async def _straggler_sync_loop() -> None:
            prefix = f"{namespace}/{straggler_mod.CONTROL_PREFIX}/"
            published: Dict[str, str] = {}
            interval = max(pol.window / 4.0, 0.05)
            lease = await drt.primary_lease()
            while True:
                await asyncio.sleep(interval)
                arbiter.evaluate(time.monotonic())
                verdicts = arbiter.verdicts()
                try:
                    for wid, state in verdicts.items():
                        if published.get(wid) != state:
                            await drt.store.put(
                                prefix + wid, state.encode(), lease=lease
                            )
                            published[wid] = state
                    for wid in [w for w in published if w not in verdicts]:
                        await drt.store.delete(prefix + wid)
                        del published[wid]
                except asyncio.CancelledError:
                    raise
                except (ConnectionError, RuntimeError, OSError):
                    # statestore blip: forget what we think is published so
                    # the next pass re-puts everything once the store heals
                    published.clear()
                    logger.warning(
                        "straggler verdict sync failed; will retry",
                        exc_info=True,
                    )

        straggler_task = asyncio.create_task(_straggler_sync_loop())

    if register:
        class _StatusEngine(AsyncEngine):
            """RPC-facing view: one item with the full cluster dump."""

            async def generate(self, request: Context):
                yield Annotated.from_data(telemetry.dump_state())

        await ns.component("telemetry").endpoint("status").serve(_StatusEngine())

    async def metrics_handler(_request):
        text = cluster.render_prometheus() + telemetry.render_process_info()
        return web.Response(text=text, content_type="text/plain", charset="utf-8")

    async def slo_handler(_request):
        return web.json_response(telemetry.dump_state())

    app = web.Application()
    app.add_routes([
        web.get("/metrics", metrics_handler),
        web.get("/debug/slo", slo_handler),
    ])
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    actual = port
    for sock in site._server.sockets:  # type: ignore[union-attr]
        actual = sock.getsockname()[1]
        break
    if bound_port is not None:
        bound_port.append(actual)
    if ready is not None:
        ready.set()
    logger.info("telemetry aggregator for %r on :%d", namespace, actual)
    try:
        await asyncio.Event().wait()
    finally:
        consumer.cancel()
        if straggler_task is not None:
            straggler_task.cancel()
        if telemetry.cluster() is cluster:
            telemetry.set_cluster(None)
        await runner.cleanup()


def main() -> None:
    p = argparse.ArgumentParser(description="dynamo_tpu telemetry aggregator")
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=9092)
    p.add_argument("--statestore", default=None)
    p.add_argument("--bus", default=None)
    p.add_argument("--expiry", type=float, default=30.0)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    async def run():
        from dynamo_tpu.runtime.distributed import DistributedRuntime

        drt = await DistributedRuntime.create(
            statestore_url=args.statestore, bus_url=args.bus
        )
        await run_telemetry_aggregator(
            drt, args.namespace, args.port, host=args.host, expiry=args.expiry
        )

    asyncio.run(run())


if __name__ == "__main__":
    main()
