"""Namespace metrics aggregator: worker load metrics → Prometheus.

Subscribes to the namespace's ``kv_metrics`` event-plane subject (the same
stream the KV router consumes), keeps the latest ForwardPassMetrics per
worker, and serves them as Prometheus gauges on ``/metrics`` — the third
observability tier (frontend Prometheus and worker push being the first
two; SURVEY.md §5).

Workers that stop publishing for ``expiry`` seconds are dropped from the
export (lease death already removes them from routing; this keeps the
dashboard honest without a registry dependency).

Re-designed from the reference's metrics component
(`components/metrics/src/lib.rs:321-594`, `main.rs:279`): the reference
scrapes NATS $SRV stats on a timer; here workers already push metrics on
the event plane, so the aggregator subscribes instead of polling.

Run:  python -m dynamo_tpu.components.metrics --namespace dynamo --port 9091
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import time
from typing import Dict, Tuple

from aiohttp import web

from dynamo_tpu.kv_router.protocols import ForwardPassMetrics
from dynamo_tpu.llm.http.metrics import escape_label as _escape_label

logger = logging.getLogger(__name__)

GAUGES = [
    ("request_active_slots", "Decode slots currently occupied"),
    ("request_total_slots", "Total decode slots"),
    ("kv_active_blocks", "KV pool blocks in use"),
    ("kv_total_blocks", "Total KV pool blocks"),
    ("num_requests_waiting", "Requests queued or awaiting remote prefill"),
    ("gpu_cache_usage_perc", "KV pool usage fraction"),
    ("gpu_prefix_cache_hit_rate", "Prefix cache hit rate"),
    # overload-protection plane (docs/overload.md): RPC pending depth,
    # cumulative admission sheds, and the drain flag per worker
    ("rpc_queue_depth", "RPC-layer pending requests (accepted, not finished)"),
    ("shed_requests", "Requests shed by admission control (cumulative)"),
    ("draining", "1 while the worker is draining (no new work routed)"),
    # health plane (docs/health.md): cumulative engine stalls and
    # reaped stuck requests per worker
    ("stalls_total", "Engine-stall detections (cumulative)"),
    ("reaped_requests_total", "Stuck requests reaped past deadline (cumulative)"),
    # live engine perf accounting (PR6, docs/observability.md): the offline
    # BENCH roofline numbers as live per-worker gauges
    ("decode_tokens_per_s", "Decode throughput EMA (tokens/s)"),
    ("step_time_ms", "Per-decode-step wall time EMA (ms)"),
    ("batch_slot_util", "Batch-slot utilization EMA (0..1)"),
    ("jit_recompiles", "Jitted step-function compilations since boot"),
    ("kv_peak_occupancy_perc", "Peak KV pool occupancy since boot (0..1)"),
    # speculative decoding + quantized KV (PR7, docs/decode_performance.md):
    # acceptance-rate EMA, cumulative draft counters, int8-KV flag
    ("spec_accept_rate", "Speculative-draft acceptance-rate EMA (0..1)"),
    ("spec_drafted_tokens", "Draft tokens handed to verify dispatches (cumulative)"),
    ("spec_accepted_tokens", "Draft tokens accepted by verify dispatches (cumulative)"),
    ("kv_quantized", "1 when the KV pool stores int8 pages with scale tables"),
    # request outcome counters (cumulative; the cluster SLO engine diffs)
    ("requests_total", "Requests served by the RPC plane (cumulative)"),
    ("requests_errored", "Requests finished in error (cumulative)"),
    # mid-stream resume (docs/resilience.md): recoveries this process made
    # and resumable streams that still died in-band (cumulative)
    ("resume_total", "Streams resumed on another worker mid-decode (cumulative)"),
    ("resume_failed_total", "Resumable streams that still failed in-band (cumulative)"),
    # live in-flight migration (docs/resilience.md §Live migration):
    # drain-time migrate-outs from this worker, failures that degraded to
    # the resume path, and KV blocks moved over the transfer plane
    ("migrations_total", "Streams live-migrated to a sibling on drain (cumulative)"),
    ("migrations_failed_total", "Drain migrations that degraded to the resume path (cumulative)"),
    ("migrate_kv_blocks_moved_total", "KV blocks moved by live migration (cumulative)"),
    # control-plane blackout tolerance (docs/resilience.md): events this
    # worker dropped from its outage buffers while the bus was down
    ("bus_dropped_events", "Events dropped from control-plane outage buffers (cumulative)"),
    # silent-corruption defense (docs/resilience.md §Silent corruption):
    # self-attributable KV checksum failures and output-watchdog trips —
    # the quarantine plane's raw signal
    ("kv_integrity_failures_total", "KV blocks that failed content checksums, attributable to this worker (cumulative)"),
    ("watchdog_trips_total", "Lanes ended by the output watchdog for non-finite/exploding logits (cumulative)"),
    # performance attribution plane (docs/observability.md §Profiling):
    # decode-dispatch device/host p95 split + device idle fraction from the
    # worker's DYN_TPU_PROFILE timeline (zeros with profiling off)
    ("dispatch_device_us_p95", "Decode dispatch block-until-ready device time p95 (us)"),
    ("dispatch_host_overhead_us_p95", "Decode dispatch host-side overhead p95 (us)"),
    ("device_idle_frac", "Fraction of the sampled window the device sat idle between dispatches"),
    # fail-slow plane (docs/resilience.md §Fail-slow): normalized dispatch
    # latency EWMA the aggregator compares against the peer median, and the
    # detector's cumulative sample counter (the freshness signal)
    ("dispatch_us_per_token_ewma", "Step-loop wall us per token, EWMA (straggler detector)"),
    ("straggler_samples_total", "Dispatches fed to the straggler detector (cumulative)"),
]

# health_state is a string on the wire; Prometheus wants a number. Unknown
# states map to the unhealthy value so a future state is never read as fine.
# quarantined (integrity plane) is graver than unhealthy: outputs untrusted.
# suspect (fail-slow plane) gets its own value: it is SOFTER than unhealthy
# (the worker still serves, outputs trusted) — before it was mapped here, a
# suspect worker fell through the unknown→2 default and dashboards read a
# merely-slow worker as down. Values are stable identifiers, not a severity
# scale; 4 was simply the next free slot.
HEALTH_STATE_VALUES = {
    "healthy": 0, "degraded": 1, "unhealthy": 2, "quarantined": 3,
    "suspect": 4,
}

# straggler_state likewise ("" / missing from pre-fail-slow workers = ok;
# anything unknown renders as suspect so a future verdict is never read as
# clean)
STRAGGLER_STATE_VALUES = {
    "": 0, "ok": 0, "suspect": 1, "confirmed": 2,
}

# control_plane_state likewise ("" from pre-blackout workers = connected;
# anything unknown renders as disconnected)
CONTROL_PLANE_STATE_VALUES = {
    "": 0, "connected": 0, "stale": 1, "disconnected": 2,
}


class MetricsAggregator:
    """Latest per-worker ForwardPassMetrics with expiry, rendered as
    Prometheus text exposition."""

    def __init__(self, namespace: str, prefix: str = "dynamo_worker", expiry: float = 30.0):
        self.namespace = namespace
        self.prefix = prefix
        self.expiry = expiry
        self._workers: Dict[str, Tuple[float, ForwardPassMetrics]] = {}
        # worker → (isl_total, overlap_total, last_event_time)
        self._hit_totals: Dict[str, Tuple[int, int, float]] = {}

    def update(self, worker_id: str, metrics: ForwardPassMetrics) -> None:
        self._workers[worker_id] = (time.monotonic(), metrics)

    def live_workers(self) -> Dict[str, ForwardPassMetrics]:
        cutoff = time.monotonic() - self.expiry
        self._workers = {
            w: (t, m) for w, (t, m) in self._workers.items() if t >= cutoff
        }
        return {w: m for w, (t, m) in self._workers.items()}

    def record_hit_rate(self, worker_id: str, isl_blocks: int, overlap_blocks: int) -> None:
        """Accumulate router KVHitRateEvents (cumulative, counter-style)."""
        isl, overlap, _ = self._hit_totals.get(worker_id, (0, 0, 0.0))
        self._hit_totals[worker_id] = (
            isl + isl_blocks, overlap + overlap_blocks, time.monotonic(),
        )

    def _prune_hit_totals(self) -> None:
        # counters for workers the router stopped routing to age out like
        # the gauges (bounded memory on churn, no lines for dead workers).
        # Hit counters get a longer horizon: routing decisions are sparser
        # than the ~1s metrics heartbeat.
        cutoff = time.monotonic() - max(self.expiry * 10, 300.0)
        self._hit_totals = {
            w: t for w, t in self._hit_totals.items() if t[2] >= cutoff
        }

    def render(self) -> str:
        live = self.live_workers()
        self._prune_hit_totals()
        lines = []
        for name, help_text in GAUGES:
            full = f"{self.prefix}_{name}"
            lines.append(f"# HELP {full} {help_text}")
            lines.append(f"# TYPE {full} gauge")
            ns_esc = _escape_label(self.namespace)
            for worker_id, m in sorted(live.items()):
                value = getattr(m, name)
                w_esc = _escape_label(str(worker_id))
                lines.append(
                    f'{full}{{namespace="{ns_esc}",worker="{w_esc}"}} {value}'
                )
        full = f"{self.prefix}_health_state"
        lines.append(
            f"# HELP {full} Worker health state "
            f"(0=healthy, 1=degraded, 2=unhealthy, 3=quarantined, 4=suspect)"
        )
        lines.append(f"# TYPE {full} gauge")
        for worker_id, m in sorted(live.items()):
            value = HEALTH_STATE_VALUES.get(
                getattr(m, "health_state", "healthy"), 2
            )
            lines.append(
                f'{full}{{namespace="{_escape_label(self.namespace)}",'
                f'worker="{_escape_label(str(worker_id))}"}} {value}'
            )
        full = f"{self.prefix}_straggler_state"
        lines.append(
            f"# HELP {full} Fail-slow verdict latched by the worker "
            f"(0=ok, 1=suspect, 2=confirmed)"
        )
        lines.append(f"# TYPE {full} gauge")
        for worker_id, m in sorted(live.items()):
            value = STRAGGLER_STATE_VALUES.get(
                getattr(m, "straggler_state", "") or "", 1
            )
            lines.append(
                f'{full}{{namespace="{_escape_label(self.namespace)}",'
                f'worker="{_escape_label(str(worker_id))}"}} {value}'
            )
        full = f"{self.prefix}_control_plane_state"
        lines.append(
            f"# HELP {full} Worker view of the control plane "
            f"(0=connected, 1=stale, 2=disconnected)"
        )
        lines.append(f"# TYPE {full} gauge")
        for worker_id, m in sorted(live.items()):
            value = CONTROL_PLANE_STATE_VALUES.get(
                getattr(m, "control_plane_state", "") or "", 2
            )
            lines.append(
                f'{full}{{namespace="{_escape_label(self.namespace)}",'
                f'worker="{_escape_label(str(worker_id))}"}} {value}'
            )
        for name, idx, help_text in (
            ("router_isl_blocks_total", 0, "Prompt blocks seen by the KV router"),
            ("router_hit_blocks_total", 1, "Prompt blocks served from prefix cache"),
        ):
            full = f"{self.prefix}_{name}"
            lines.append(f"# HELP {full} {help_text}")
            lines.append(f"# TYPE {full} counter")
            for worker_id, totals in sorted(self._hit_totals.items()):
                lines.append(
                    f'{full}{{namespace="{_escape_label(self.namespace)}",worker="{_escape_label(str(worker_id))}"}} {totals[idx]}'
                )
        # request-phase latency quantiles (runtime/tracing.py span durations,
        # summarized worker-side by attach_kv_publishing): one gauge per
        # (worker, phase, quantile) plus a sample-count companion
        full = f"{self.prefix}_phase_latency_ms"
        lines.append(
            f"# HELP {full} Request-phase latency quantile from trace spans"
        )
        lines.append(f"# TYPE {full} gauge")
        count_lines = []
        ns_esc = _escape_label(self.namespace)
        for worker_id, m in sorted(live.items()):
            phases = getattr(m, "phase_latency", None)
            if not isinstance(phases, dict):
                continue
            w_esc = _escape_label(str(worker_id))
            for phase in sorted(phases):
                stats = phases[phase]
                if not isinstance(stats, dict):
                    continue
                p_esc = _escape_label(str(phase))
                for q in ("p50", "p95", "p99"):
                    val = stats.get(f"{q}_ms")
                    if val is None:
                        continue
                    lines.append(
                        f'{full}{{namespace="{ns_esc}",worker="{w_esc}",'
                        f'phase="{p_esc}",quantile="{q}"}} {val}'
                    )
                count_lines.append(
                    f'{self.prefix}_phase_latency_count{{namespace="{ns_esc}",'
                    f'worker="{w_esc}",phase="{p_esc}"}} '
                    f'{int(stats.get("count", 0))}'
                )
        full = f"{self.prefix}_phase_latency_count"
        lines.append(f"# HELP {full} Samples behind the phase latency quantiles")
        lines.append(f"# TYPE {full} gauge")
        lines.extend(count_lines)
        # per-worker uptime (satellite: `dynamo_uptime_seconds` everywhere a
        # process exposes metrics; workers push theirs on the stream)
        full = f"{self.prefix}_uptime_seconds"
        lines.append(f"# HELP {full} Seconds since the worker process started")
        lines.append(f"# TYPE {full} gauge")
        for worker_id, m in sorted(live.items()):
            up = float(getattr(m, "uptime_s", 0.0) or 0.0)
            if up > 0:
                lines.append(
                    f'{full}{{namespace="{ns_esc}",'
                    f'worker="{_escape_label(str(worker_id))}"}} {up:g}'
                )
        full = f"{self.prefix}_up"
        lines.append(f"# HELP {full} Workers currently reporting metrics")
        lines.append(f"# TYPE {full} gauge")
        lines.append(f'{full}{{namespace="{_escape_label(self.namespace)}"}} {len(live)}')
        out = "\n".join(lines) + "\n"
        # this process's own uptime + build identity, and — when a cluster
        # telemetry aggregator is co-hosted — the cluster section
        try:
            from dynamo_tpu.runtime import telemetry

            out += telemetry.render_process_info()
            out += telemetry.render_cluster_metrics()
        except Exception:  # telemetry unavailable must never break /metrics
            pass
        return out


async def run_aggregator(
    drt, namespace: str, port: int, host: str = "0.0.0.0", expiry: float = 30.0
) -> None:
    """Subscribe to kv_metrics and serve /metrics until cancelled."""
    from dynamo_tpu.runtime.distributed import (
        KV_HIT_RATE_SUBJECT,
        KV_METRICS_SUBJECT,
        resubscribe_forever,
    )

    agg = MetricsAggregator(namespace, expiry=expiry)
    ns = drt.namespace(namespace)
    consumers = [
        asyncio.create_task(resubscribe_forever(
            ns, KV_METRICS_SUBJECT,
            lambda d: agg.update(
                d["worker_id"], ForwardPassMetrics.from_dict(d["metrics"])
            ),
        )),
        asyncio.create_task(resubscribe_forever(
            ns, KV_HIT_RATE_SUBJECT,
            lambda d: agg.record_hit_rate(
                d["worker_id"], d["isl_blocks"], d["overlap_blocks"]
            ),
        )),
    ]

    async def metrics_handler(_request):
        return web.Response(
            text=agg.render(), content_type="text/plain", charset="utf-8"
        )

    app = web.Application()
    app.add_routes([web.get("/metrics", metrics_handler)])
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    logger.info("metrics aggregator for %r on :%d/metrics", namespace, port)
    try:
        await asyncio.Event().wait()
    finally:
        for c in consumers:
            c.cancel()
        await runner.cleanup()


def main() -> None:
    p = argparse.ArgumentParser(description="dynamo_tpu metrics aggregator")
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=9091)
    p.add_argument("--statestore", default=None)
    p.add_argument("--bus", default=None)
    p.add_argument("--expiry", type=float, default=30.0)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    async def run():
        from dynamo_tpu.runtime.distributed import DistributedRuntime

        drt = await DistributedRuntime.create(
            statestore_url=args.statestore, bus_url=args.bus
        )
        await run_aggregator(
            drt, args.namespace, args.port, host=args.host, expiry=args.expiry
        )

    asyncio.run(run())


if __name__ == "__main__":
    main()
