"""Model Deployment Card (MDC): serializable model identity.

Captures everything a frontend/preprocessor needs to serve a model — tokenizer,
chat template, context length, special tokens — plus a content checksum so
distributed components can verify they agree on the model.
Reference parity: lib/llm/src/model_card/{model.rs:55-361,create.rs:41-143}.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass
class ModelDeploymentCard:
    display_name: str
    model_path: Optional[str] = None
    context_length: int = 4096
    tokenizer_file: Optional[str] = None  # path to tokenizer.json (HF fast format)
    chat_template: Optional[str] = None
    bos_token: Optional[str] = None
    eos_token: Optional[str] = None
    eos_token_ids: list[int] = field(default_factory=list)
    model_config: dict[str, Any] = field(default_factory=dict)
    mdcsum: Optional[str] = None
    gguf_path: Optional[str] = None  # set when the model came from a .gguf

    @classmethod
    def from_gguf(cls, path: str, display_name: Optional[str] = None) -> "ModelDeploymentCard":
        """Build from a single .gguf file: config + tokenizer are extracted
        to a sidecar HF-layout dir; weights load straight from the GGUF.

        Reference: ModelDeploymentCard::from_gguf (model_card/create.rs:41-96).
        """
        from dynamo_tpu.llm.gguf import extract_model_dir

        hf_dir = extract_model_dir(path)
        name = display_name or os.path.basename(path).removesuffix(".gguf")
        card = cls.from_local_path(hf_dir, name)
        card.gguf_path = path
        return card

    @classmethod
    def from_repo(
        cls, repo_id: str, display_name: Optional[str] = None,
        revision: Optional[str] = None,
    ) -> "ModelDeploymentCard":
        """Build from a hub repo id (``org/name``): resolve to local files —
        fixture hub (``DYN_HUB_DIR``), then the HF cache, then a download —
        and delegate to :meth:`from_local_path`.

        Reference: hub download resolution (launch/dynamo-run/src/hub.rs).
        """
        path = resolve_repo(repo_id, revision=revision)
        return cls.from_local_path(path, display_name or repo_id)

    @classmethod
    def from_local_path(cls, path: str, display_name: Optional[str] = None) -> "ModelDeploymentCard":
        """Build from an HF-layout model directory (config.json + tokenizer
        files) or a single .gguf file.

        Reference: ModelDeploymentCard::from_local_path (model_card/create.rs:41).
        """
        if path.endswith(".gguf"):
            return cls.from_gguf(path, display_name)
        name = display_name or os.path.basename(os.path.normpath(path))
        card = cls(display_name=name, model_path=path)

        cfg_path = os.path.join(path, "config.json")
        if os.path.exists(cfg_path):
            with open(cfg_path) as f:
                card.model_config = json.load(f)
            card.context_length = int(
                card.model_config.get("max_position_embeddings")
                or card.model_config.get("n_positions")
                or card.context_length
            )
            eos = card.model_config.get("eos_token_id")
            if isinstance(eos, int):
                card.eos_token_ids = [eos]
            elif isinstance(eos, list):
                card.eos_token_ids = [int(e) for e in eos]

        tok_json = os.path.join(path, "tokenizer.json")
        if os.path.exists(tok_json):
            card.tokenizer_file = tok_json

        tok_cfg_path = os.path.join(path, "tokenizer_config.json")
        if os.path.exists(tok_cfg_path):
            with open(tok_cfg_path) as f:
                tok_cfg = json.load(f)
            card.chat_template = tok_cfg.get("chat_template")
            card.bos_token = _token_str(tok_cfg.get("bos_token"))
            card.eos_token = _token_str(tok_cfg.get("eos_token"))

        card.mdcsum = card.checksum()
        return card

    def checksum(self) -> str:
        """Stable content hash over the serialized card (reference: mdcsum)."""
        payload = {
            "display_name": self.display_name,
            "context_length": self.context_length,
            "chat_template": self.chat_template,
            "bos_token": self.bos_token,
            "eos_token": self.eos_token,
            "eos_token_ids": self.eos_token_ids,
        }
        if self.tokenizer_file and os.path.exists(self.tokenizer_file):
            h = hashlib.blake2b(digest_size=8)
            with open(self.tokenizer_file, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
            payload["tokenizer_digest"] = h.hexdigest()
        return hashlib.blake2b(
            json.dumps(payload, sort_keys=True).encode(), digest_size=16
        ).hexdigest()

    # -- wire form (registered into the statestore for discovery) ----------

    def to_dict(self) -> dict:
        from dataclasses import asdict

        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "ModelDeploymentCard":
        return cls(**d)


class CardStore:
    """Persisted model deployment cards, content-addressed by mdcsum.

    Workers publish their card once; frontends/operators fetch it by
    checksum instead of re-deriving it from model files they may not have.
    Entries carry an explicit expiry refreshed on publish — stale cards
    (model deleted, worker gone for good) age out rather than accumulating.
    Reference: MDC persistence with checksum + expiry
    (model_card/model.rs:150-193).
    """

    def __init__(self, store, namespace: str, ttl: float = 24 * 3600.0):
        self.store = store
        self.prefix = f"{namespace}/mdc/"
        self.ttl = ttl

    async def publish(self, card: "ModelDeploymentCard") -> str:
        import time as _time

        mdcsum = card.mdcsum or card.checksum()
        payload = dict(card.to_dict(), mdcsum=mdcsum,
                       expires_at=_time.time() + self.ttl)
        await self.store.put(
            self.prefix + mdcsum, json.dumps(payload).encode()
        )
        return mdcsum

    async def load(self, mdcsum: str) -> Optional["ModelDeploymentCard"]:
        import time as _time

        raw = await self.store.get(self.prefix + mdcsum)
        if raw is None:
            return None
        d = json.loads(raw)
        if d.pop("expires_at", 0) < _time.time():
            # expired for THIS read — but deleting here would race a
            # concurrent publish() refresh; purging is purge_expired()'s job
            return None
        return ModelDeploymentCard.from_dict(d)

    async def purge_expired(self, grace: Optional[float] = None) -> int:
        """Delete entries expired for longer than ``grace`` (default ttl/2 —
        a card merely past its expiry may be mid-refresh by its publisher;
        one well past it is abandoned). Returns the purge count."""
        import time as _time

        grace = self.ttl / 2 if grace is None else grace
        cutoff = _time.time() - grace
        purged = 0
        for key, raw in (await self.store.get_prefix(self.prefix)).items():
            try:
                if json.loads(raw).get("expires_at", 0) < cutoff:
                    await self.store.delete(key)
                    purged += 1
            except ValueError:
                await self.store.delete(key)
                purged += 1
        return purged


_REPO_ID_PART = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]*$")


def looks_like_repo_id(spec: str) -> bool:
    """``org/name``: exactly one slash, hub-legal segments, and no existing
    file/dir of that name. Deliberately cwd-independent beyond the existence
    check — a dir named after the org must not shadow a valid hub id; the
    mistyped-local-path case gets its clear error in :func:`resolve_repo`."""
    if os.path.exists(spec) or spec.count("/") != 1:
        return False
    if spec.startswith((".", "/", "~")):
        return False
    org, name = spec.split("/")
    return bool(_REPO_ID_PART.match(org) and _REPO_ID_PART.match(name))


def resolve_repo(repo_id: str, revision: Optional[str] = None) -> str:
    """Resolve a hub repo id to a local model directory.

    Order (first hit wins):
    1. ``DYN_HUB_DIR``: an operator-managed local hub — a directory holding
       one model dir per repo, named ``org--name`` (also how tests provide a
       fixture hub without network).
    2. The HF cache (``snapshot_download(local_files_only=True)``) — a model
       already pulled by any HF tool serves without touching the network.
    3. A fresh ``snapshot_download`` of configs + tokenizer + safetensors/
       gguf (reference downloads the same set, hub.rs).
    """
    hub_dir = os.environ.get("DYN_HUB_DIR")
    if hub_dir:
        cand = os.path.join(hub_dir, repo_id.replace("/", "--"))
        if os.path.isdir(cand):
            return cand
    from huggingface_hub import snapshot_download

    patterns = [
        "*.json", "*.safetensors", "*.gguf", "tokenizer*", "*.model",
    ]
    try:
        return snapshot_download(
            repo_id, revision=revision, local_files_only=True,
            allow_patterns=patterns,
        )
    except Exception:
        pass
    try:
        return snapshot_download(
            repo_id, revision=revision, allow_patterns=patterns
        )
    except Exception as e:
        # a NOT-FOUND hub answer for an id whose org segment exists as a
        # local directory is almost certainly a mistyped relative path
        # (e.g. models/llama) — surface that interpretation. Transient
        # network/auth failures propagate untouched: rewriting those would
        # mislead a user whose hub id is actually valid.
        if _is_hub_not_found(e) and os.path.isdir(repo_id.split("/")[0]):
            raise FileNotFoundError(
                f"{repo_id!r}: not found on the hub, and no local file "
                f"{repo_id!r} exists (directory {repo_id.split('/')[0]!r} "
                "does — mistyped local path?)"
            ) from e
        raise


def _is_hub_not_found(e: Exception) -> bool:
    try:
        from huggingface_hub.utils import (
            EntryNotFoundError,
            LocalEntryNotFoundError,
            RepositoryNotFoundError,
            RevisionNotFoundError,
        )
    except ImportError:
        return False
    return isinstance(
        e,
        (
            RepositoryNotFoundError,
            RevisionNotFoundError,
            EntryNotFoundError,
            LocalEntryNotFoundError,
        ),
    )


def _token_str(raw: Any) -> Optional[str]:
    """tokenizer_config token entries are either strings or {'content': ...}."""
    if raw is None:
        return None
    if isinstance(raw, str):
        return raw
    if isinstance(raw, dict):
        return raw.get("content")
    return None
