"""Built-in fake backends: echo engines.

Deterministic token streams at a configurable rate, used to exercise the full
serving path (frontend, pipelines, routing, SSE) without a model.
Reference parity: EchoEngineCore / EchoEngineFull with DYN_TOKEN_ECHO_DELAY_MS,
default 10 ms/token = 100 tok/s (lib/llm/src/engines.rs:80-178).
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator

from ..runtime.annotated import Annotated
from ..runtime.engine import AsyncEngine, Context
from .protocols.common import FinishReason, LLMEngineOutput, PreprocessedRequest

def _echo_delay_s() -> float:
    from ..runtime.config import env_float

    return env_float("TOKEN_ECHO_DELAY_MS", 10.0) / 1000.0


class EchoEngineCore(AsyncEngine[PreprocessedRequest, Annotated[dict]]):
    """Token-in/token-out echo: replays the prompt tokens one per tick."""

    def __init__(self, delay_s: float | None = None):
        self._delay_s = delay_s

    async def generate(
        self, request: Context[PreprocessedRequest]
    ) -> AsyncIterator[Annotated[dict]]:
        delay = self._delay_s if self._delay_s is not None else _echo_delay_s()
        req = request.data
        explicit_max = req.stop_conditions.max_tokens
        max_tokens = explicit_max if explicit_max is not None else len(req.token_ids)
        emitted = 0
        for tok in req.token_ids:
            if request.context.is_stopped or emitted >= max_tokens:
                break
            if delay > 0:
                await asyncio.sleep(delay)
            emitted += 1
            yield Annotated.from_data(
                LLMEngineOutput(token_ids=[tok]).to_dict(), id=request.id
            )
        reason = FinishReason.CANCELLED if request.context.is_stopped else (
            FinishReason.LENGTH
            if explicit_max is not None and emitted >= explicit_max
            else FinishReason.EOS
        )
        yield Annotated.from_data(LLMEngineOutput.final(reason).to_dict(), id=request.id)


class EchoEngineFull(AsyncEngine):
    """OpenAI-request-in echo: streams the last user message back word by word.

    Needs no tokenizer/model files — the quickest full-path fake backend.
    Reference: EchoEngineFull (lib/llm/src/engines.rs:80-178).
    """

    def __init__(self, delay_s: float | None = None):
        self._delay_s = delay_s

    async def generate(self, request: Context) -> AsyncIterator[Annotated[dict]]:
        from .protocols.openai import (
            ChatCompletionRequest,
            DeltaGenerator,
            new_request_id,
        )

        delay = self._delay_s if self._delay_s is not None else _echo_delay_s()
        req = request.data
        if isinstance(req, ChatCompletionRequest):
            text = req.messages[-1].text_content() if req.messages else ""
            chat = True
        else:  # CompletionRequest
            prompt = req.prompt
            text = prompt if isinstance(prompt, str) else " ".join(map(str, prompt))
            chat = False
        gen = DeltaGenerator(new_request_id("chatcmpl" if chat else "cmpl"), req.model, chat=chat)
        words = text.split()
        if chat:
            explicit_max = req.effective_max_tokens()
        else:
            explicit_max = req.max_tokens
        max_tokens = explicit_max if explicit_max is not None else max(len(words), 1)

        emitted = 0
        for i, word in enumerate(words):
            if request.context.is_stopped or emitted >= max_tokens:
                break
            if delay > 0:
                await asyncio.sleep(delay)
            piece = word if i == 0 else " " + word
            emitted += 1
            chunk = gen.text_chunk(piece)
            yield Annotated.from_data(chunk.model_dump(exclude_none=True), id=request.id)
        reason = FinishReason.CANCELLED if request.context.is_stopped else (
            FinishReason.LENGTH if emitted >= max_tokens and emitted < len(words) else FinishReason.EOS
        )
        final = gen.finish_chunk(reason)
        yield Annotated.from_data(final.model_dump(exclude_none=True), id=request.id)


class CounterEngine(AsyncEngine):
    """Streams integers 0..n-1; error injection for HTTP-service tests.

    Reference analogue: the CounterEngine in lib/llm/tests/http-service.rs:41-186.
    """

    def __init__(self, n: int = 10, fail_at: int | None = None):
        self._n = n
        self._fail_at = fail_at

    async def generate(self, request: Context) -> AsyncIterator[Annotated[int]]:
        for i in range(self._n):
            if request.context.is_stopped:
                break
            if self._fail_at is not None and i == self._fail_at:
                yield Annotated.from_error(f"injected failure at {i}", id=request.id)
                return
            yield Annotated.from_data(i, id=request.id)
