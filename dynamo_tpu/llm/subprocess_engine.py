"""Crash-isolated bring-your-own-engine host.

The reference runs external engines as subprocess children: a ZMQ ipc socket
pair with msgpack framing, a ready handshake over a passed fd, stdout/stderr
scraped into the host's logs, and crash isolation so a dying engine never
takes the worker down (lib/engines/sglang/src/{worker.rs:784,subprocess.rs},
lib/engines/vllm0_7/src/worker.rs:797). TPU-build equivalent, re-designed on
asyncio: a fork/exec child speaking the framed two-part codec
(runtime/codec.py) over an inherited unix socketpair.

- **ready handshake**: the child loads the user engine, then sends a
  ``{"ready": true}`` frame; the parent won't serve until it arrives.
- **log scraping**: child stdout/stderr lines re-emit through the parent's
  ``logging`` under ``user-engine`` (stderr at WARNING).
- **crash isolation**: an EOF on the pair fails every in-flight request with
  a clean error item; with ``restart_on_crash`` the child respawns with
  backoff and NEW requests proceed (in-flight ones are failed, not replayed).
- **crash-loop protection**: the restart backoff is capped and, crucially,
  NOT reset by a start that dies again within ``min_uptime`` — a child that
  crashes right after its ready handshake escalates the delay instead of
  hot-looping. After ``max_fast_crashes`` consecutive fast crashes the host
  stops respawning, fails pending requests, and reports itself
  ``unhealthy`` through the health plane (``health_state`` is swept by
  runtime/health.py's HealthMonitor, which self-drains the worker).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import socket
import sys
from typing import Any, AsyncIterator, Dict, Optional

from dynamo_tpu.runtime.annotated import Annotated
from dynamo_tpu.runtime.codec import TwoPartMessage, read_frame, write_frame
from dynamo_tpu.runtime.engine import AsyncEngine, Context

logger = logging.getLogger(__name__)
_ENGINE_FD_ENV = "DYN_TPU_ENGINE_FD"


def load_user_engine(path: str):
    """Load a bring-your-own-engine python file.

    The file must expose an AsyncEngine instance named ``engine``, a factory
    ``make_engine()`` returning one, or a module-level async generator
    function ``generate(request)`` (wrapped automatically).
    Reference: `lib/engines/python/src/lib.rs:78-382` (pystr:/pytok:).
    """
    import importlib.util

    spec = importlib.util.spec_from_file_location("dyn_user_engine", path)
    if spec is None or spec.loader is None:
        raise RuntimeError(f"cannot load user engine file {path!r}")
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)

    if hasattr(module, "engine"):
        return module.engine
    if hasattr(module, "make_engine"):
        return module.make_engine()
    if hasattr(module, "generate"):

        class _FnEngine(AsyncEngine):
            async def generate(self, request):
                async for item in module.generate(request):
                    yield item

        return _FnEngine()
    raise RuntimeError(
        f"user engine {path!r} must define `engine`, `make_engine()`, or `generate()`"
    )


def _serialize_request(data: Any) -> tuple:
    """(kind, json-able payload) for the wire."""
    if hasattr(data, "to_dict"):
        return type(data).__name__, data.to_dict()
    if hasattr(data, "model_dump"):
        return "dict", data.model_dump(exclude_none=True)
    return "dict", data


def _deserialize_request(kind: str, payload: Any):
    if kind == "PreprocessedRequest":
        from dynamo_tpu.llm.protocols.common import PreprocessedRequest

        return PreprocessedRequest.from_dict(payload)
    return payload


class SubprocessEngine(AsyncEngine):
    """AsyncEngine proxy around a user engine running in a child process."""

    def __init__(
        self,
        user_path: str,
        restart_on_crash: bool = True,
        ready_timeout: float = 60.0,
        restart_backoff: float = 0.5,
        max_restart_backoff: float = 10.0,
        min_uptime: float = 5.0,
        max_fast_crashes: int = 5,
        env: Optional[Dict[str, str]] = None,
    ):
        self.user_path = user_path
        self.restart_on_crash = restart_on_crash
        self.ready_timeout = ready_timeout
        self.restart_backoff = restart_backoff
        self.max_restart_backoff = max_restart_backoff
        # a child that survives less than this after its ready handshake is
        # a *fast crash*: the backoff keeps escalating instead of resetting
        self.min_uptime = min_uptime
        self.max_fast_crashes = max(1, max_fast_crashes)
        # extra environment for the child (merged over the parent's): how a
        # host passes engine config (model paths, device selection) without
        # polluting its own process env — the reference passes env to its
        # child engines the same way
        self.extra_env = dict(env) if env else {}
        self._proc: Optional[asyncio.subprocess.Process] = None
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._sock: Optional[socket.socket] = None
        self._streams: Dict[str, asyncio.Queue] = {}
        self._send_lock = asyncio.Lock()
        self._closing = False
        self._tasks: list = []
        self._ready = asyncio.Event()
        self._restart_task: Optional[asyncio.Task] = None
        self._start_lock: Optional[asyncio.Lock] = None
        # crash-loop state (see module docstring): escalating delay that
        # only resets after a child survives min_uptime, plus the
        # consecutive-fast-crash counter behind the give-up circuit
        self._restart_delay = restart_backoff
        self._fast_crashes = 0
        self._ready_at: Optional[float] = None
        self._gave_up = False
        # health-plane self-report, swept by HealthMonitor.check(): flips to
        # "unhealthy" when the crash loop gives up, which self-drains the
        # worker instead of hot-looping a doomed child forever
        self.health_state = "healthy"

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> None:
        parent_sock, child_sock = socket.socketpair()
        parent_sock.setblocking(False)
        self._sock = parent_sock
        env = dict(os.environ)
        env.update(self.extra_env)
        env[_ENGINE_FD_ENV] = str(child_sock.fileno())
        self._proc = await asyncio.create_subprocess_exec(
            sys.executable, "-u", "-m", "dynamo_tpu.llm.subprocess_engine",
            self.user_path,
            pass_fds=(child_sock.fileno(),),
            env=env,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE,
        )
        child_sock.close()
        self._reader, self._writer = await asyncio.open_connection(sock=parent_sock)
        self._tasks = [
            asyncio.create_task(self._scrape(self._proc.stdout, logging.INFO)),
            asyncio.create_task(self._scrape(self._proc.stderr, logging.WARNING)),
        ]
        # ready handshake before the read loop takes over the stream
        try:
            frame = await asyncio.wait_for(
                read_frame(self._reader), self.ready_timeout
            )
        except (asyncio.TimeoutError, asyncio.IncompleteReadError, ConnectionError) as e:
            await self._kill_child()
            raise RuntimeError(
                f"user engine {self.user_path!r} failed to become ready: {e}"
            ) from e
        header = json.loads(frame.header)
        if not header.get("ready"):
            await self._kill_child()
            raise RuntimeError(
                f"user engine {self.user_path!r} handshake error: "
                f"{header.get('error', 'unknown')}"
            )
        self._ready.set()
        self._ready_at = asyncio.get_running_loop().time()
        self._tasks.append(asyncio.create_task(self._read_loop()))
        logger.info(
            "user engine %s running in subprocess pid=%d",
            self.user_path, self._proc.pid,
        )

    async def _kill_child(self) -> None:
        if self._proc is not None and self._proc.returncode is None:
            try:
                self._proc.kill()
            except ProcessLookupError:
                pass
            await self._proc.wait()
        if self._writer is not None:
            self._writer.close()

    async def close(self) -> None:
        self._closing = True
        if self._restart_task is not None:
            self._restart_task.cancel()
        try:
            if self._writer is not None:
                async with self._send_lock:
                    await write_frame(
                        self._writer,
                        TwoPartMessage(json.dumps({"op": "shutdown"}).encode(), b""),
                    )
                if self._proc is not None:
                    await asyncio.wait_for(self._proc.wait(), 5.0)
        except (asyncio.TimeoutError, ConnectionError, OSError):
            pass
        await self._kill_child()
        for t in self._tasks:
            t.cancel()

    async def _scrape(self, stream, level: int) -> None:
        """Re-emit child output through the framework's logging."""
        if stream is None:
            return
        try:
            while True:
                line = await stream.readline()
                if not line:
                    return
                logger.log(level, "[user-engine] %s", line.decode(errors="replace").rstrip())
        except asyncio.CancelledError:
            pass

    # -- wire ----------------------------------------------------------------

    async def _read_loop(self) -> None:
        try:
            while True:
                frame = await read_frame(self._reader)
                h = json.loads(frame.header)
                q = self._streams.get(h.get("id"))
                if q is None:
                    continue
                kind = h.get("kind")
                if kind == "item":
                    q.put_nowait(("item", json.loads(frame.body)))
                elif kind == "end":
                    q.put_nowait(("end", None))
                elif kind == "error":
                    q.put_nowait(("error", h.get("message", "engine error")))
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError):
            pass
        # child gone (crash or shutdown): fail every in-flight request
        exit_code = self._proc.returncode if self._proc else None
        for q in self._streams.values():
            q.put_nowait(
                ("error", f"engine subprocess died (exit={exit_code})")
            )
        self._ready.clear()
        if self._closing or not self.restart_on_crash:
            return
        # crash-loop accounting: a child that died within min_uptime of
        # ready is a fast crash — escalate, don't reset, the backoff
        uptime = None
        if self._ready_at is not None:
            uptime = asyncio.get_running_loop().time() - self._ready_at
        if uptime is not None and uptime >= self.min_uptime:
            self._fast_crashes = 0
            self._restart_delay = self.restart_backoff
        else:
            self._fast_crashes += 1
        if self._fast_crashes >= self.max_fast_crashes:
            # give up: respawning a child that dies in under min_uptime
            # max_fast_crashes times in a row only burns CPU and masks the
            # failure. Mark unhealthy — the health plane self-drains the
            # worker — and fail requests fast instead of hot-looping.
            self._gave_up = True
            self.health_state = "unhealthy"
            # wake requests parked in generate()'s ready wait — they
            # re-check _gave_up and fail fast instead of burning the full
            # ready_timeout against a child that will never come back
            self._ready.set()
            logger.error(
                "user engine %s crash-looping (%d consecutive crashes "
                "within %.1fs of ready): giving up, worker marked unhealthy",
                self.user_path, self._fast_crashes, self.min_uptime,
            )
            return
        logger.warning(
            "user engine subprocess died (exit=%s, uptime=%s); restarting "
            "in %.1fs (fast crashes: %d/%d)",
            exit_code,
            f"{uptime:.1f}s" if uptime is not None else "?",
            self._restart_delay, self._fast_crashes, self.max_fast_crashes,
        )
        self._restart_task = asyncio.create_task(self._restart())

    async def _restart(self) -> None:
        while not self._closing:
            delay = self._restart_delay
            # capped escalation, persisted across crash-loop cycles (the
            # old code reset to the base on every successful start, so a
            # child crashing right after ready hot-looped at the base delay)
            self._restart_delay = min(
                self._restart_delay * 2, self.max_restart_backoff
            )
            await asyncio.sleep(delay)
            try:
                await self.start()
                return
            except (RuntimeError, OSError) as e:
                logger.error("user engine restart failed: %s", e)
                self._fast_crashes += 1
                if self._fast_crashes >= self.max_fast_crashes:
                    self._gave_up = True
                    self.health_state = "unhealthy"
                    self._ready.set()  # wake parked requests to fail fast
                    logger.error(
                        "user engine %s failed %d consecutive (re)starts: "
                        "giving up, worker marked unhealthy",
                        self.user_path, self._fast_crashes,
                    )
                    return

    # -- AsyncEngine ---------------------------------------------------------

    def _gave_up_error(self) -> Annotated:
        return Annotated.from_error(
            f"engine subprocess {self.user_path!r} crash-looped and was "
            f"shut down (worker unhealthy)"
        )

    async def generate(self, request: Context) -> AsyncIterator[Annotated]:
        if self._gave_up:
            # crash loop gave up: fail fast with a terminal error instead of
            # letting callers wait out ready_timeout against a dead child
            yield self._gave_up_error()
            return
        if self._start_lock is None:
            self._start_lock = asyncio.Lock()
        async with self._start_lock:
            if self._proc is None and not self._closing:
                # lazy spawn on first use (build paths are synchronous)
                await self.start()
        if not self._ready.is_set():
            try:
                await asyncio.wait_for(self._ready.wait(), self.ready_timeout)
            except asyncio.TimeoutError:
                yield Annotated.from_error("engine subprocess unavailable")
                return
        if self._gave_up:
            # the give-up fired while we were parked on the ready wait
            # (it sets _ready to wake us): same fast terminal error
            yield self._gave_up_error()
            return
        rid = request.id
        kind, payload = _serialize_request(request.data)
        q: asyncio.Queue = asyncio.Queue()
        self._streams[rid] = q
        try:
            try:
                async with self._send_lock:
                    await write_frame(
                        self._writer,
                        TwoPartMessage(
                            json.dumps(
                                {"op": "generate", "id": rid, "type": kind}
                            ).encode(),
                            json.dumps(payload).encode(),
                        ),
                    )
            except (ConnectionError, OSError) as e:
                yield Annotated.from_error(f"engine subprocess unreachable: {e}")
                return
            while True:
                if request.context.is_stopped:
                    try:
                        async with self._send_lock:
                            await write_frame(
                                self._writer,
                                TwoPartMessage(
                                    json.dumps({"op": "cancel", "id": rid}).encode(),
                                    b"",
                                ),
                            )
                    except (ConnectionError, OSError):
                        pass
                    return
                try:
                    what, value = await asyncio.wait_for(q.get(), 0.5)
                except asyncio.TimeoutError:
                    continue  # poll is_stopped
                if what == "item":
                    yield Annotated.from_dict(value)
                elif what == "error":
                    yield Annotated.from_error(value)
                    return
                else:  # end
                    return
        finally:
            self._streams.pop(rid, None)


# =========================================================================
# child entrypoint: python -m dynamo_tpu.llm.subprocess_engine <user_file>
# =========================================================================


async def _child_main(user_path: str) -> None:
    # not an operator knob: the parent hands the socket fd to the child it
    # just spawned, and a missing value is a launch-protocol bug that MUST
    # raise (KeyError) rather than degrade to a default
    fd = int(os.environ[_ENGINE_FD_ENV])  # dynlint: disable=knob-discipline
    sock = socket.socket(fileno=fd)
    sock.setblocking(False)
    reader, writer = await asyncio.open_connection(sock=sock)

    try:
        engine = load_user_engine(user_path)
    except Exception as e:  # report over the pair, then exit nonzero
        await write_frame(
            writer,
            TwoPartMessage(
                json.dumps({"ready": False, "error": str(e)}).encode(), b""
            ),
        )
        raise SystemExit(1)
    await write_frame(
        writer, TwoPartMessage(json.dumps({"ready": True}).encode(), b"")
    )

    send_lock = asyncio.Lock()
    contexts: Dict[str, Context] = {}

    async def run_request(rid: str, req: Context) -> None:
        try:
            async for item in engine.generate(req):
                if isinstance(item, Annotated):
                    wire = item.to_dict()
                elif isinstance(item, dict):
                    wire = {"data": item}
                else:
                    wire = {"data": item}
                async with send_lock:
                    await write_frame(
                        writer,
                        TwoPartMessage(
                            json.dumps({"id": rid, "kind": "item"}).encode(),
                            json.dumps(wire).encode(),
                        ),
                    )
            async with send_lock:
                await write_frame(
                    writer,
                    TwoPartMessage(
                        json.dumps({"id": rid, "kind": "end"}).encode(), b""
                    ),
                )
        except Exception as e:
            logging.getLogger("dyn_user_engine").exception("generate failed")
            try:
                async with send_lock:
                    await write_frame(
                        writer,
                        TwoPartMessage(
                            json.dumps(
                                {"id": rid, "kind": "error", "message": str(e)}
                            ).encode(),
                            b"",
                        ),
                    )
            except (ConnectionError, OSError):
                pass
        finally:
            contexts.pop(rid, None)

    tasks = set()
    while True:
        try:
            frame = await read_frame(reader)
        except (asyncio.IncompleteReadError, ConnectionError):
            return  # parent gone
        h = json.loads(frame.header)
        op = h.get("op")
        if op == "shutdown":
            return
        if op == "cancel":
            ctx = contexts.get(h.get("id"))
            if ctx is not None:
                ctx.context.stop_generating()
            continue
        if op == "generate":
            rid = h["id"]
            payload = _deserialize_request(
                h.get("type", "dict"), json.loads(frame.body)
            )
            req = Context(payload, request_id=rid)
            contexts[rid] = req
            t = asyncio.create_task(run_request(rid, req))
            tasks.add(t)
            t.add_done_callback(tasks.discard)


def main() -> None:
    logging.basicConfig(level=logging.INFO)
    asyncio.run(_child_main(sys.argv[1]))


if __name__ == "__main__":
    main()
