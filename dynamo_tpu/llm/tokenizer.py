"""Tokenizer wrapper: encode, incremental streaming decode, stop-sequence jail.

Wraps HF `tokenizers.Tokenizer` (the same underlying Rust library the reference
uses) and adds the two serving-side pieces every streaming LLM needs:

- :class:`DecodeStream` — incremental detokenization that never emits half a
  UTF-8 codepoint or half a multi-token grapheme (prefix/read-offset scheme).
- :class:`StopSequenceDecoder` — the "jail": text that partially matches a stop
  string is held back until disambiguated, and matched stop strings are never
  emitted.

Reference parity: lib/llm/src/tokenizers.rs:91-570 (Encoding, DecodeStream,
StopSequenceDecoder with jail states).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Optional, Sequence

from tokenizers import Tokenizer


class HFTokenizer:
    """Thin wrapper over a HF fast tokenizer file (tokenizer.json)."""

    def __init__(self, tokenizer: Tokenizer):
        self._tk = tokenizer

    @classmethod
    def from_file(cls, path: str) -> "HFTokenizer":
        return cls(Tokenizer.from_file(path))

    def encode(self, text: str, add_special_tokens: bool = False) -> list[int]:
        return self._tk.encode(text, add_special_tokens=add_special_tokens).ids

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        return self._tk.decode(list(ids), skip_special_tokens=skip_special_tokens)

    def token_to_id(self, token: str) -> Optional[int]:
        return self._tk.token_to_id(token)

    @property
    def vocab_size(self) -> int:
        return self._tk.get_vocab_size()

    def decode_stream(self, skip_special_tokens: bool = True) -> "DecodeStream":
        return DecodeStream(self, skip_special_tokens=skip_special_tokens)


class DecodeStream:
    """Incremental detokenizer.

    Decodes a growing id sequence and only emits text once it is stable: if the
    freshly decoded suffix ends in U+FFFD (a partial UTF-8 sequence from a split
    multi-byte token), emission waits for more tokens.
    """

    def __init__(self, tokenizer: HFTokenizer, skip_special_tokens: bool = True):
        self._tk = tokenizer
        self._skip_special = skip_special_tokens
        self._ids: list[int] = []
        self._prefix_offset = 0
        self._read_offset = 0

    def step(self, token_id: int) -> Optional[str]:
        """Feed one token id; return newly stable text (or None)."""
        self._ids.append(token_id)
        prefix_text = self._tk.decode(
            self._ids[self._prefix_offset : self._read_offset], self._skip_special
        )
        full_text = self._tk.decode(self._ids[self._prefix_offset :], self._skip_special)
        if full_text.endswith("�"):
            # partial multi-byte sequence: hold until complete
            return None
        new_text = full_text[len(prefix_text) :]
        self._prefix_offset = self._read_offset
        self._read_offset = len(self._ids)
        return new_text if new_text else None


class JailState(str, enum.Enum):
    OPEN = "open"  # text flows freely
    JAILED = "jailed"  # partial stop-sequence match held back
    STOPPED = "stopped"  # full stop-sequence matched; stream complete


@dataclass
class StopDecision:
    text: Optional[str]  # text safe to emit now (None = nothing new)
    stopped: bool  # a stop sequence fully matched
    stop_token: bool = False  # stopped because of a stop *token id*


class StopSequenceDecoder:
    """Streaming decode with hidden stop sequences.

    Combines a :class:`DecodeStream` with stop-string matching. Text that could
    be the beginning of a stop string is "jailed" (withheld); once the match
    fails it is released, once it completes the stream stops and the stop text
    itself is never emitted. Reference: StopSequenceDecoder jail states
    (lib/llm/src/tokenizers.rs).
    """

    def __init__(
        self,
        tokenizer: HFTokenizer,
        stop_sequences: Sequence[str] = (),
        stop_token_ids: Sequence[int] = (),
        hidden: bool = True,
        skip_special_tokens: bool = True,
    ):
        self._decode = DecodeStream(tokenizer, skip_special_tokens)
        self._stops = [s for s in stop_sequences if s]
        self._stop_ids = set(stop_token_ids)
        self._hidden = hidden
        self._pending = ""  # jailed text
        self._state = JailState.OPEN

    @property
    def state(self) -> JailState:
        return self._state

    def step(self, token_id: int) -> StopDecision:
        if self._state is JailState.STOPPED:
            return StopDecision(text=None, stopped=True)

        if token_id in self._stop_ids:
            self._state = JailState.STOPPED
            # release whatever was jailed (it was not a stop string after all,
            # but the request ended on a stop token)
            text = self._pending or None
            self._pending = ""
            return StopDecision(text=text, stopped=True, stop_token=True)

        piece = self._decode.step(token_id)
        if piece is None:
            return StopDecision(text=None, stopped=False)

        buf = self._pending + piece

        if self._stops:
            # full match anywhere in the buffer?
            earliest = -1
            for s in self._stops:
                idx = buf.find(s)
                if idx != -1 and (earliest == -1 or idx < earliest):
                    earliest = idx
            if earliest != -1:
                self._state = JailState.STOPPED
                self._pending = ""
                emit = buf[:earliest] if self._hidden else buf
                return StopDecision(text=emit or None, stopped=True)

            # partial match at the tail → jail that suffix
            jail_len = _longest_stop_prefix_suffix(buf, self._stops)
            if jail_len > 0:
                emit = buf[:-jail_len]
                self._pending = buf[-jail_len:]
                self._state = JailState.JAILED
                return StopDecision(text=emit or None, stopped=False)

        self._pending = ""
        self._state = JailState.OPEN
        return StopDecision(text=buf or None, stopped=False)

    def flush(self) -> Optional[str]:
        """Release any jailed text at end of stream (no stop ever matched)."""
        text, self._pending = self._pending, ""
        return text or None


def _longest_stop_prefix_suffix(buf: str, stops: Sequence[str]) -> int:
    """Length of the longest buffer-suffix that is a proper prefix of any stop."""
    best = 0
    for s in stops:
        max_k = min(len(buf), len(s) - 1)
        for k in range(max_k, best, -1):
            if buf.endswith(s[:k]):
                best = k
                break
    return best
