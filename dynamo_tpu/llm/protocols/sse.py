"""Server-Sent Events line codec.

Parses and emits SSE messages (``data:``, ``event:``, ``:`` comments, id) and the
OpenAI ``[DONE]`` sentinel, symmetric with the :class:`Annotated` envelope.
Reference parity: SseLineCodec / Message / create_message_stream
(lib/llm/src/protocols/codec.rs:36-295).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import AsyncIterator, Iterable, Optional

from ...runtime.annotated import Annotated

DONE_SENTINEL = "[DONE]"


@dataclass
class SseMessage:
    data: Optional[str] = None
    event: Optional[str] = None
    id: Optional[str] = None
    comments: list[str] = field(default_factory=list)

    @property
    def is_done(self) -> bool:
        return self.data is not None and self.data.strip() == DONE_SENTINEL

    def encode(self) -> str:
        """Render as an SSE frame (without the trailing blank-line separator)."""
        lines: list[str] = []
        for c in self.comments:
            lines.append(f": {c}")
        if self.event is not None:
            lines.append(f"event: {self.event}")
        if self.id is not None:
            lines.append(f"id: {self.id}")
        if self.data is not None:
            for chunk in self.data.split("\n"):
                lines.append(f"data: {chunk}")
        return "\n".join(lines)

    @classmethod
    def from_annotated(cls, item: Annotated) -> "SseMessage":
        return cls(
            data=None if item.data is None else json.dumps(item.data),
            event=item.event,
            id=item.id,
            comments=list(item.comment),
        )

    def to_annotated(self) -> Annotated:
        return Annotated(
            data=None if self.data is None else json.loads(self.data),
            event=self.event,
            id=self.id,
            comment=list(self.comments),
        )


class SseDecoder:
    """Incremental SSE parser: feed lines, get complete messages.

    A message is terminated by a blank line. Multiple ``data:`` lines concatenate
    with newlines, per the SSE spec.
    """

    def __init__(self) -> None:
        self._data_lines: list[str] = []
        self._event: Optional[str] = None
        self._id: Optional[str] = None
        self._comments: list[str] = []

    def _flush(self) -> Optional[SseMessage]:
        if not self._data_lines and self._event is None and not self._comments and self._id is None:
            return None
        msg = SseMessage(
            data="\n".join(self._data_lines) if self._data_lines else None,
            event=self._event,
            id=self._id,
            comments=self._comments,
        )
        self._data_lines = []
        self._event = None
        self._id = None
        self._comments = []
        return msg

    def feed_line(self, line: str) -> Optional[SseMessage]:
        line = line.rstrip("\r\n")
        if line == "":
            return self._flush()
        if line.startswith(":"):
            self._comments.append(line[1:].lstrip())
            return None
        if ":" in line:
            name, value = line.split(":", 1)
            value = value.lstrip()
        else:
            name, value = line, ""
        if name == "data":
            self._data_lines.append(value)
        elif name == "event":
            self._event = value
        elif name == "id":
            self._id = value
        # unknown fields are ignored per spec
        return None

    def feed_lines(self, lines: Iterable[str]) -> list[SseMessage]:
        out = []
        for line in lines:
            msg = self.feed_line(line)
            if msg is not None:
                out.append(msg)
        tail = self._flush()
        if tail is not None:
            out.append(tail)
        return out


async def decode_sse_stream(lines: AsyncIterator[str]) -> AsyncIterator[SseMessage]:
    """Decode an async stream of lines into SSE messages."""
    decoder = SseDecoder()
    async for line in lines:
        msg = decoder.feed_line(line)
        if msg is not None:
            yield msg
