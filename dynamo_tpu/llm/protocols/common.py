"""Engine-agnostic internal request/response protocol.

The preprocessor lowers OpenAI requests into a :class:`PreprocessedRequest`
(token ids + stop conditions + sampling options); engines emit
:class:`LLMEngineOutput` items which the backend detokenizes into
:class:`BackendOutput`. Reference parity: lib/llm/src/protocols/common.rs:52-644,
common/llm_backend.rs:27-126, common/preprocessor.rs:25.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Optional


class HttpError(Exception):
    """An error with an HTTP status, raisable from any pipeline stage.

    The frontend maps it to a JSON error response (or an in-band SSE error
    event if headers were already sent). Reference parity: HttpError in
    lib/bindings/python (SURVEY.md §2.4).
    """

    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


class FinishReason(str, enum.Enum):
    EOS = "eos"
    LENGTH = "length"
    STOP = "stop"
    ERROR = "error"
    CANCELLED = "cancelled"

    def to_openai(self) -> str:
        if self is FinishReason.LENGTH:
            return "length"
        if self is FinishReason.ERROR:
            return "error"
        return "stop"


@dataclass
class StopConditions:
    """Reference: StopConditions (lib/llm/src/protocols/common.rs)."""

    max_tokens: Optional[int] = None
    stop: list[str] = field(default_factory=list)
    stop_token_ids: list[int] = field(default_factory=list)
    min_tokens: Optional[int] = None
    ignore_eos: bool = False


@dataclass
class SamplingOptions:
    """Reference: SamplingOptions (lib/llm/src/protocols/common.rs)."""

    n: Optional[int] = None
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None
    frequency_penalty: Optional[float] = None
    presence_penalty: Optional[float] = None
    seed: Optional[int] = None
    # None = no logprobs; 0 = chosen-token logprob only; k>0 = also the
    # top-k alternative tokens per step
    logprobs: Optional[int] = None


@dataclass
class PreprocessedRequest:
    """Token-level request handed to an engine (a.k.a. BackendInput).

    Reference: PreprocessedRequest / BackendInput
    (lib/llm/src/protocols/common/preprocessor.rs:25, common/llm_backend.rs).
    """

    token_ids: list[int]
    stop_conditions: StopConditions = field(default_factory=StopConditions)
    sampling_options: SamplingOptions = field(default_factory=SamplingOptions)
    eos_token_ids: list[int] = field(default_factory=list)
    annotations: list[str] = field(default_factory=list)
    mdc_sum: Optional[str] = None
    # mid-stream resume marker (runtime/resilience.StreamJournal): when the
    # routing client re-admits a broken stream as prompt+generated, this
    # carries {"prompt_len": where the ORIGINAL prompt ended inside
    # token_ids, "rng_offset": draws the original stream consumed}. Engines
    # rebuild sampling state (penalty counts over token_ids[prompt_len:])
    # from it; None (the wire default) is exactly the pre-resume request.
    resume: Optional[dict] = None
    # live-migration attach marker (disagg/migration.py): the staged
    # migration id a re-homed client presents to the target engine so
    # admission adopts the pre-shipped KV (zero recomputed positions)
    # instead of re-prefilling. None (the wire default) is exactly the
    # pre-migration request; an unknown/expired id degrades to the resume
    # recompute path.
    migrate: Optional[str] = None

    def to_dict(self) -> dict:
        from dataclasses import asdict

        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "PreprocessedRequest":
        return cls(
            token_ids=list(d["token_ids"]),
            stop_conditions=StopConditions(**d.get("stop_conditions", {})),
            sampling_options=SamplingOptions(**d.get("sampling_options", {})),
            eos_token_ids=list(d.get("eos_token_ids", [])),
            annotations=list(d.get("annotations", [])),
            mdc_sum=d.get("mdc_sum"),
            resume=d.get("resume") if isinstance(d.get("resume"), dict) else None,
            migrate=(
                str(d["migrate"])
                if isinstance(d.get("migrate"), (str, int)) else None
            ),
        )


@dataclass
class LLMEngineOutput:
    """One streamed step from an engine: newly generated token ids.

    Reference: LLMEngineOutput (lib/llm/src/protocols/common/llm_backend.rs:27-126).
    `text` is optional engine-side detokenization used only for validation; the
    canonical text comes from the Backend decoder.
    """

    token_ids: list[int] = field(default_factory=list)
    text: Optional[str] = None
    cum_log_probs: Optional[float] = None
    finish_reason: Optional[FinishReason] = None
    # per-token log-probabilities (parallel to token_ids) and, when the
    # request asked for alternatives, per-token {token_id: logprob} maps
    log_probs: Optional[list[float]] = None
    top_logprobs: Optional[list[dict[int, float]]] = None
    # engine-specific side data (e.g. kv hit-rate annotations)
    extra: dict[str, Any] = field(default_factory=dict)

    @classmethod
    def final(cls, reason: FinishReason) -> "LLMEngineOutput":
        return cls(finish_reason=reason)

    def to_dict(self) -> dict:
        out: dict[str, Any] = {"token_ids": self.token_ids}
        if self.text is not None:
            out["text"] = self.text
        if self.cum_log_probs is not None:
            out["cum_log_probs"] = self.cum_log_probs
        if self.finish_reason is not None:
            out["finish_reason"] = self.finish_reason.value
        if self.log_probs is not None:
            out["log_probs"] = self.log_probs
        if self.top_logprobs is not None:
            # JSON object keys are strings; from_dict restores ints
            out["top_logprobs"] = [
                {str(k): v for k, v in d.items()} for d in self.top_logprobs
            ]
        if self.extra:
            out["extra"] = self.extra
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "LLMEngineOutput":
        fr = d.get("finish_reason")
        top = d.get("top_logprobs")
        return cls(
            token_ids=list(d.get("token_ids", [])),
            text=d.get("text"),
            cum_log_probs=d.get("cum_log_probs"),
            finish_reason=FinishReason(fr) if fr else None,
            log_probs=d.get("log_probs"),
            top_logprobs=(
                [{int(k): v for k, v in t.items()} for t in top]
                if top is not None
                else None
            ),
            extra=dict(d.get("extra", {})),
        )


@dataclass
class BackendOutput:
    """Detokenized output leaving the Backend post-processor.

    Reference: BackendOutput (lib/llm/src/protocols/common/llm_backend.rs).
    """

    token_ids: list[int] = field(default_factory=list)
    text: Optional[str] = None
    finish_reason: Optional[FinishReason] = None
    cum_log_probs: Optional[float] = None
    log_probs: Optional[list[float]] = None
    top_logprobs: Optional[list[dict[int, float]]] = None
