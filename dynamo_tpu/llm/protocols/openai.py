"""OpenAI-compatible protocol types: chat completions and completions.

Request/response models (pydantic), per-token delta generators, and stream→full
aggregators for the non-streaming path. The ``nvext`` extension block is kept
name-compatible with the reference so existing clients work unchanged.
Reference parity: lib/llm/src/protocols/openai/{chat_completions,completions}.rs,
aggregator.rs, delta.rs, nvext.rs.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Literal, Optional, Union

from pydantic import BaseModel, ConfigDict, Field

from .common import FinishReason


class NvExt(BaseModel):
    """NVIDIA-compatible extension block (reference: nvext.rs:193)."""

    model_config = ConfigDict(extra="allow")

    ignore_eos: Optional[bool] = None
    annotations: Optional[list[str]] = None
    use_raw_prompt: Optional[bool] = None
    greed_sampling: Optional[bool] = None


class ChatMessage(BaseModel):
    model_config = ConfigDict(extra="allow")

    role: str
    content: Optional[Union[str, list[dict]]] = None
    name: Optional[str] = None
    tool_calls: Optional[list[dict]] = None

    def text_content(self) -> str:
        if isinstance(self.content, str):
            return self.content
        if isinstance(self.content, list):
            return "".join(
                part.get("text", "") for part in self.content if part.get("type") == "text"
            )
        return ""


class StreamOptions(BaseModel):
    include_usage: Optional[bool] = None


class ChatCompletionRequest(BaseModel):
    model_config = ConfigDict(extra="allow")

    model: str
    messages: list[ChatMessage]
    stream: bool = False
    stream_options: Optional[StreamOptions] = None
    max_tokens: Optional[int] = None
    max_completion_tokens: Optional[int] = None
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None  # common extension
    n: Optional[int] = None
    stop: Optional[Union[str, list[str]]] = None
    seed: Optional[int] = None
    frequency_penalty: Optional[float] = None
    presence_penalty: Optional[float] = None
    logprobs: Optional[bool] = None
    top_logprobs: Optional[int] = None
    min_tokens: Optional[int] = None  # common extension
    tools: Optional[list[dict]] = None
    tool_choice: Optional[Union[str, dict]] = None
    nvext: Optional[NvExt] = None

    def stop_list(self) -> list[str]:
        if self.stop is None:
            return []
        return [self.stop] if isinstance(self.stop, str) else list(self.stop)

    def effective_max_tokens(self) -> Optional[int]:
        return self.max_completion_tokens or self.max_tokens


class CompletionRequest(BaseModel):
    model_config = ConfigDict(extra="allow")

    model: str
    prompt: Union[str, list[str], list[int], list[list[int]]]
    stream: bool = False
    stream_options: Optional[StreamOptions] = None
    max_tokens: Optional[int] = None
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None
    n: Optional[int] = None
    stop: Optional[Union[str, list[str]]] = None
    seed: Optional[int] = None
    frequency_penalty: Optional[float] = None
    presence_penalty: Optional[float] = None
    echo: Optional[bool] = None
    logprobs: Optional[int] = None  # number of alternatives per token
    suffix: Optional[str] = None  # FIM insertion — rejected unless supported
    nvext: Optional[NvExt] = None

    def stop_list(self) -> list[str]:
        if self.stop is None:
            return []
        return [self.stop] if isinstance(self.stop, str) else list(self.stop)


class Usage(BaseModel):
    prompt_tokens: int = 0
    completion_tokens: int = 0
    total_tokens: int = 0


class ChatDelta(BaseModel):
    model_config = ConfigDict(extra="allow")

    role: Optional[str] = None
    content: Optional[str] = None


class ChatChunkChoice(BaseModel):
    index: int = 0
    delta: ChatDelta = Field(default_factory=ChatDelta)
    finish_reason: Optional[str] = None
    logprobs: Optional[dict] = None  # {"content": [TokenLogprob, ...]}


class ChatCompletionChunk(BaseModel):
    id: str
    object: Literal["chat.completion.chunk"] = "chat.completion.chunk"
    created: int = Field(default_factory=lambda: int(time.time()))
    model: str = ""
    choices: list[ChatChunkChoice] = Field(default_factory=list)
    usage: Optional[Usage] = None


class ChatChoice(BaseModel):
    index: int = 0
    message: ChatMessage = Field(default_factory=lambda: ChatMessage(role="assistant", content=""))
    finish_reason: Optional[str] = None
    logprobs: Optional[dict] = None


class ChatCompletionResponse(BaseModel):
    id: str
    object: Literal["chat.completion"] = "chat.completion"
    created: int = Field(default_factory=lambda: int(time.time()))
    model: str = ""
    choices: list[ChatChoice] = Field(default_factory=list)
    usage: Optional[Usage] = None


class CompletionChoice(BaseModel):
    index: int = 0
    text: str = ""
    finish_reason: Optional[str] = None
    # legacy completions format: {"tokens", "token_logprobs", "top_logprobs"}
    logprobs: Optional[dict] = None


class CompletionChunk(BaseModel):
    id: str
    object: Literal["text_completion"] = "text_completion"
    created: int = Field(default_factory=lambda: int(time.time()))
    model: str = ""
    choices: list[CompletionChoice] = Field(default_factory=list)
    usage: Optional[Usage] = None


class CompletionResponse(BaseModel):
    id: str
    object: Literal["text_completion"] = "text_completion"
    created: int = Field(default_factory=lambda: int(time.time()))
    model: str = ""
    choices: list[CompletionChoice] = Field(default_factory=list)
    usage: Optional[Usage] = None


class ModelInfo(BaseModel):
    id: str
    object: Literal["model"] = "model"
    created: int = Field(default_factory=lambda: int(time.time()))
    owned_by: str = "dynamo_tpu"


class ModelList(BaseModel):
    object: Literal["list"] = "list"
    data: list[ModelInfo] = Field(default_factory=list)


def new_request_id(prefix: str = "chatcmpl") -> str:
    return f"{prefix}-{uuid.uuid4().hex}"


# ---------------------------------------------------------------------------
# Delta generation (reference: delta.rs)
# ---------------------------------------------------------------------------


class DeltaGenerator:
    """Builds OpenAI chunk objects from detokenized backend text deltas."""

    def __init__(self, request_id: str, model: str, chat: bool = True):
        self.request_id = request_id
        self.model = model
        self.chat = chat
        self.created = int(time.time())
        self._started: set[int] = set()  # choice indexes that got their role
        self.usage = Usage()

    def text_chunk(self, text: str, index: int = 0, logprobs: Optional[dict] = None):
        if self.chat:
            delta = ChatDelta(content=text)
            if index not in self._started:
                delta.role = "assistant"
                self._started.add(index)
            return ChatCompletionChunk(
                id=self.request_id,
                created=self.created,
                model=self.model,
                choices=[ChatChunkChoice(index=index, delta=delta, logprobs=logprobs)],
            )
        return CompletionChunk(
            id=self.request_id,
            created=self.created,
            model=self.model,
            choices=[CompletionChoice(index=index, text=text, logprobs=logprobs)],
        )

    def finish_chunk(self, reason: FinishReason, index: int = 0, usage: Optional[Usage] = None):
        fr = reason.to_openai()
        if self.chat:
            return ChatCompletionChunk(
                id=self.request_id,
                created=self.created,
                model=self.model,
                choices=[ChatChunkChoice(index=index, finish_reason=fr)],
                usage=usage,
            )
        return CompletionChunk(
            id=self.request_id,
            created=self.created,
            model=self.model,
            choices=[CompletionChoice(index=index, text="", finish_reason=fr)],
            usage=usage,
        )


# ---------------------------------------------------------------------------
# Stream → full aggregation (reference: aggregator.rs)
# ---------------------------------------------------------------------------


def aggregate_chat_chunks(chunks: list[dict | ChatCompletionChunk]) -> ChatCompletionResponse:
    """Fold a chunk stream into one chat.completion response."""
    parsed = [
        c if isinstance(c, ChatCompletionChunk) else ChatCompletionChunk.model_validate(c)
        for c in chunks
    ]
    if not parsed:
        raise ValueError("empty chunk stream")
    by_index: dict[int, ChatChoice] = {}
    usage: Optional[Usage] = None
    for chunk in parsed:
        if chunk.usage is not None:
            usage = chunk.usage
        for ch in chunk.choices:
            agg = by_index.setdefault(
                ch.index, ChatChoice(index=ch.index, message=ChatMessage(role="assistant", content=""))
            )
            if ch.delta.role:
                agg.message.role = ch.delta.role
            if ch.delta.content:
                agg.message.content = (agg.message.content or "") + ch.delta.content
            if ch.logprobs and ch.logprobs.get("content"):
                agg.logprobs = agg.logprobs or {"content": []}
                agg.logprobs["content"].extend(ch.logprobs["content"])
            if ch.finish_reason:
                agg.finish_reason = ch.finish_reason
    first = parsed[0]
    return ChatCompletionResponse(
        id=first.id,
        created=first.created,
        model=first.model,
        choices=[by_index[i] for i in sorted(by_index)],
        usage=usage,
    )


def aggregate_completion_chunks(chunks: list[dict | CompletionChunk]) -> CompletionResponse:
    parsed = [
        c if isinstance(c, CompletionChunk) else CompletionChunk.model_validate(c) for c in chunks
    ]
    if not parsed:
        raise ValueError("empty chunk stream")
    by_index: dict[int, CompletionChoice] = {}
    usage: Optional[Usage] = None
    for chunk in parsed:
        if chunk.usage is not None:
            usage = chunk.usage
        for ch in chunk.choices:
            agg = by_index.setdefault(ch.index, CompletionChoice(index=ch.index, text=""))
            agg.text += ch.text
            if ch.logprobs:
                agg.logprobs = agg.logprobs or {
                    "tokens": [], "token_logprobs": [], "top_logprobs": [],
                }
                for key in ("tokens", "token_logprobs", "top_logprobs"):
                    agg.logprobs[key].extend(ch.logprobs.get(key, []))
            if ch.finish_reason:
                agg.finish_reason = ch.finish_reason
    first = parsed[0]
    return CompletionResponse(
        id=first.id,
        created=first.created,
        model=first.model,
        choices=[by_index[i] for i in sorted(by_index)],
        usage=usage,
    )
