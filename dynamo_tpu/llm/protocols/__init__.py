"""Wire protocol types for the LLM serving plane.

``ENDPOINT_PROTOCOLS`` is the project's endpoint→protocol registry:
every endpoint name that appears as a string literal in the package
(``component.endpoint("...")``) must have an entry here or in
``dynamo_tpu/kv_router/protocols.py`` naming the endpoint's anchoring
wire type — the request protocol its workers deserialize, or, for
poll-style endpoints whose request carries no payload, the reply type
(noted per entry). The ``endpoint-protocol-drift`` dynlint rule
cross-checks both directions — an unregistered endpoint name and a
registry entry pointing at a deleted protocol class both fail the lint
(docs/static_analysis.md).
"""

# endpoint name → "dotted.module:ProtocolSymbol" of the request type
ENDPOINT_PROTOCOLS = {
    # the serving endpoint every LLM worker registers (cli/run.py
    # run_endpoint; name comes from the dyn://ns.comp.ep spec, "generate"
    # by convention); carries a preprocessed token-in/token-out request
    "generate": "dynamo_tpu.llm.protocols.common:PreprocessedRequest",
    # pull-based metrics scrape plane (runtime/distributed.py
    # serve_stats_endpoint): the request carries no payload, so the entry
    # anchors the REPLY type
    "stats": "dynamo_tpu.kv_router.protocols:ForwardPassMetrics",
    # telemetry aggregator's cluster-state endpoint
    # (components/telemetry_aggregator.py): payload-less request, entry
    # anchors the REPLY type (the telemetry_dump state)
    "status": "dynamo_tpu.runtime.telemetry:TelemetryDump",
    # planner's decision-ring endpoint (components/planner.py): payload-less
    # request, entry anchors the REPLY type (`llmctl planner status` reads it)
    "plan": "dynamo_tpu.components.planner:PlannerStatus",
}
