"""GGUF model-file support: metadata, tokenizer, and tensor loading.

Parses the GGUF binary container (magic ``GGUF``, little-endian, v2/v3):
header → metadata key/values → tensor infos → aligned tensor data. From a
single .gguf file the framework recovers:

- the model architecture/config (``llama.*`` metadata) → LlamaConfig,
- the embedded tokenizer (``tokenizer.ggml.*``) → a HuggingFace-format
  ``tokenizer.json`` (byte-level BPE), so the whole serving stack
  (preprocessor, detokenizer, chat template) works without HF sidecar
  files,
- tensor data for F32/F16/BF16 tensors → numpy (quantized GGML block
  formats are rejected with a clear error — dequantization is out of
  scope for serving bf16 on TPU).

Re-designed from the reference's GGUF support
(`lib/llm/src/gguf/{content.rs:53,gguf_metadata.rs,gguf_tokenizer.rs:114}`,
~950 LoC Rust): same capability (metadata + tokenizer + config extraction
for serving), implemented against the GGUF spec, not translated.
"""

from __future__ import annotations

import json
import os
import struct
from dataclasses import dataclass, field
from typing import Any, BinaryIO, Dict, List, Optional, Tuple

import numpy as np

GGUF_MAGIC = 0x46554747  # "GGUF" little-endian

# metadata value types (spec)
T_UINT8, T_INT8, T_UINT16, T_INT16, T_UINT32, T_INT32 = 0, 1, 2, 3, 4, 5
T_FLOAT32, T_BOOL, T_STRING, T_ARRAY, T_UINT64, T_INT64, T_FLOAT64 = (
    6, 7, 8, 9, 10, 11, 12,
)

# ggml tensor dtypes we can load directly
GGML_F32, GGML_F16 = 0, 1
GGML_BF16 = 30
# BF16 has no portable numpy dtype: read raw uint16 and upconvert via a
# <<16 bit shift into float32 (exact — bf16 is float32's top half)
_LOADABLE = {GGML_F32: np.float32, GGML_F16: np.float16, GGML_BF16: np.uint16}

_SCALAR_FMT = {
    T_UINT8: "<B", T_INT8: "<b", T_UINT16: "<H", T_INT16: "<h",
    T_UINT32: "<I", T_INT32: "<i", T_FLOAT32: "<f", T_UINT64: "<Q",
    T_INT64: "<q", T_FLOAT64: "<d",
}


@dataclass
class GgufTensorInfo:
    name: str
    shape: Tuple[int, ...]  # logical shape, row-major (reversed from file)
    ggml_type: int
    offset: int  # relative to data section start


@dataclass
class GgufFile:
    path: str
    version: int
    metadata: Dict[str, Any]
    tensors: Dict[str, GgufTensorInfo]
    data_start: int
    alignment: int

    # -- convenience -----------------------------------------------------------

    @property
    def architecture(self) -> str:
        return self.metadata.get("general.architecture", "unknown")

    def arch_key(self, key: str) -> Any:
        return self.metadata.get(f"{self.architecture}.{key}")

    def load_tensor(self, name: str) -> np.ndarray:
        info = self.tensors.get(name)
        if info is None:
            raise KeyError(f"tensor {name!r} not in {self.path}")
        if info.ggml_type not in _LOADABLE:
            raise ValueError(
                f"tensor {name!r} has ggml type {info.ggml_type} (quantized?) — "
                "only F32/F16/BF16 GGUF tensors are loadable; re-export unquantized"
            )
        dt = _LOADABLE[info.ggml_type]
        count = int(np.prod(info.shape)) if info.shape else 1
        with open(self.path, "rb") as f:
            f.seek(self.data_start + info.offset)
            raw = f.read(count * np.dtype(dt).itemsize)
        arr = np.frombuffer(raw, dtype=dt)
        if info.ggml_type == GGML_BF16:
            arr = (arr.astype(np.uint32) << 16).view(np.float32)
        return arr.reshape(info.shape)


def _read_str(f: BinaryIO) -> str:
    (n,) = struct.unpack("<Q", f.read(8))
    return f.read(n).decode("utf-8", errors="replace")


def _read_value(f: BinaryIO, vtype: int) -> Any:
    fmt = _SCALAR_FMT.get(vtype)
    if fmt is not None:
        (v,) = struct.unpack(fmt, f.read(struct.calcsize(fmt)))
        return v
    if vtype == T_BOOL:
        return bool(f.read(1)[0])
    if vtype == T_STRING:
        return _read_str(f)
    if vtype == T_ARRAY:
        (etype,) = struct.unpack("<I", f.read(4))
        (n,) = struct.unpack("<Q", f.read(8))
        return [_read_value(f, etype) for _ in range(n)]
    raise ValueError(f"unknown GGUF metadata type {vtype}")


def read_gguf(path: str) -> GgufFile:
    """Parse header, metadata, and tensor infos (tensor data stays on disk)."""
    with open(path, "rb") as f:
        magic, version = struct.unpack("<II", f.read(8))
        if magic != GGUF_MAGIC:
            raise ValueError(f"{path} is not a GGUF file (magic {magic:#x})")
        if version not in (2, 3):
            raise ValueError(f"unsupported GGUF version {version}")
        tensor_count, kv_count = struct.unpack("<QQ", f.read(16))

        metadata: Dict[str, Any] = {}
        for _ in range(kv_count):
            key = _read_str(f)
            (vtype,) = struct.unpack("<I", f.read(4))
            metadata[key] = _read_value(f, vtype)

        tensors: Dict[str, GgufTensorInfo] = {}
        for _ in range(tensor_count):
            name = _read_str(f)
            (n_dims,) = struct.unpack("<I", f.read(4))
            dims = struct.unpack(f"<{n_dims}Q", f.read(8 * n_dims))
            (ggml_type,) = struct.unpack("<I", f.read(4))
            (offset,) = struct.unpack("<Q", f.read(8))
            # GGUF stores dims innermost-first; numpy wants row-major
            tensors[name] = GgufTensorInfo(
                name=name, shape=tuple(reversed(dims)),
                ggml_type=ggml_type, offset=offset,
            )

        alignment = int(metadata.get("general.alignment", 32))
        pos = f.tell()
        data_start = (pos + alignment - 1) // alignment * alignment
        return GgufFile(
            path=path, version=version, metadata=metadata, tensors=tensors,
            data_start=data_start, alignment=alignment,
        )


# ---------------------------------------------------------------------------
# tokenizer extraction → HF tokenizer.json
# ---------------------------------------------------------------------------


def write_hf_tokenizer(gguf: GgufFile, out_dir: str) -> str:
    """Convert the embedded ``tokenizer.ggml.*`` vocab to HF tokenizer files.

    Supports the ``gpt2`` (byte-level BPE) tokenizer model, which covers the
    llama3/qwen GGUF exports this framework serves. Writes tokenizer.json +
    tokenizer_config.json (chat template included when embedded) and returns
    out_dir.
    """
    md = gguf.metadata
    model = md.get("tokenizer.ggml.model")
    if model != "gpt2":
        raise ValueError(
            f"embedded tokenizer model {model!r} unsupported (byte-level BPE "
            "'gpt2' only)"
        )
    tokens: List[str] = md["tokenizer.ggml.tokens"]
    merges: List[str] = md.get("tokenizer.ggml.merges", [])
    token_types: List[int] = md.get("tokenizer.ggml.token_type", [])

    vocab = {tok: i for i, tok in enumerate(tokens)}
    added = [
        {
            "id": i, "content": tokens[i], "single_word": False,
            "lstrip": False, "rstrip": False, "normalized": False,
            "special": True,
        }
        for i, t in enumerate(token_types)
        if t == 3  # CONTROL
    ]
    tokenizer_json = {
        "version": "1.0",
        "truncation": None,
        "padding": None,
        "added_tokens": added,
        "normalizer": None,
        "pre_tokenizer": {
            "type": "ByteLevel", "add_prefix_space": False,
            "trim_offsets": True, "use_regex": True,
        },
        "post_processor": None,
        "decoder": {
            "type": "ByteLevel", "add_prefix_space": True,
            "trim_offsets": True, "use_regex": True,
        },
        "model": {
            "type": "BPE",
            "dropout": None,
            "unk_token": None,
            "continuing_subword_prefix": None,
            "end_of_word_suffix": None,
            "fuse_unk": False,
            "byte_fallback": False,
            "vocab": vocab,
            "merges": [m.split(" ", 1) for m in merges],
        },
    }
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "tokenizer.json"), "w") as f:
        json.dump(tokenizer_json, f)

    bos_id = md.get("tokenizer.ggml.bos_token_id")
    eos_id = md.get("tokenizer.ggml.eos_token_id")
    tok_cfg = {
        "bos_token": tokens[bos_id] if bos_id is not None else None,
        "eos_token": tokens[eos_id] if eos_id is not None else None,
        "chat_template": md.get("tokenizer.chat_template"),
    }
    with open(os.path.join(out_dir, "tokenizer_config.json"), "w") as f:
        json.dump({k: v for k, v in tok_cfg.items() if v is not None}, f)
    return out_dir


# ---------------------------------------------------------------------------
# model config extraction
# ---------------------------------------------------------------------------


def model_config_dict(gguf: GgufFile) -> dict:
    """``llama.*`` metadata → the HF-config-shaped dict the model builder
    consumes (same keys as config.json)."""
    if gguf.architecture not in ("llama", "qwen2"):
        raise ValueError(f"unsupported GGUF architecture {gguf.architecture!r}")
    heads = int(gguf.arch_key("attention.head_count"))
    kv_heads = int(gguf.arch_key("attention.head_count_kv") or heads)
    embed = int(gguf.arch_key("embedding_length"))
    return {
        "architectures": ["LlamaForCausalLM"],
        "model_type": gguf.architecture,
        "vocab_size": len(gguf.metadata.get("tokenizer.ggml.tokens", []))
        or int(gguf.arch_key("vocab_size") or 0),
        "hidden_size": embed,
        "intermediate_size": int(gguf.arch_key("feed_forward_length")),
        "num_hidden_layers": int(gguf.arch_key("block_count")),
        "num_attention_heads": heads,
        "num_key_value_heads": kv_heads,
        "head_dim": embed // heads,
        "rope_theta": float(gguf.arch_key("rope.freq_base") or 10000.0),
        "rms_norm_eps": float(
            gguf.arch_key("attention.layer_norm_rms_epsilon") or 1e-5
        ),
        "max_position_embeddings": int(gguf.arch_key("context_length") or 4096),
        "bos_token_id": gguf.metadata.get("tokenizer.ggml.bos_token_id"),
        "eos_token_id": gguf.metadata.get("tokenizer.ggml.eos_token_id"),
        "tie_word_embeddings": "output.weight" not in gguf.tensors,
    }


def extract_model_dir(gguf_path: str, out_dir: Optional[str] = None) -> str:
    """One-call GGUF → HF-layout directory (config.json + tokenizer files).

    The serving stack consumes HF-layout dirs (ModelDeploymentCard); this
    materializes one next to the .gguf so ``--model-path model.gguf`` works
    end-to-end. Weight tensors stay in the .gguf (see gguf_params()).
    """
    gguf = read_gguf(gguf_path)
    out_dir = out_dir or gguf_path + ".hf"
    os.makedirs(out_dir, exist_ok=True)
    write_hf_tokenizer(gguf, out_dir)
    with open(os.path.join(out_dir, "config.json"), "w") as f:
        json.dump(model_config_dict(gguf), f, indent=1)
    return out_dir


# GGUF ↔ framework tensor-name mapping (llama family)
_TENSOR_MAP = {
    "token_embd.weight": "embed",
    "output_norm.weight": "final_norm",
    "output.weight": "lm_head",
}
_LAYER_MAP = {
    "attn_norm.weight": "attn_norm",
    "attn_q.weight": "wq",
    "attn_k.weight": "wk",
    "attn_v.weight": "wv",
    "attn_output.weight": "wo",
    "ffn_norm.weight": "mlp_norm",
    "ffn_gate.weight": "w_gate",
    "ffn_up.weight": "w_up",
    "ffn_down.weight": "w_down",
}


def gguf_params(gguf: GgufFile, config, dtype=None) -> dict:
    """Load GGUF tensors into the model's stacked-layer param pytree.

    GGUF stores projection matrices as [out, in]; the model computes
    ``x @ W`` with W [in, out], so weights transpose on load.
    """
    import jax.numpy as jnp

    dt = dtype or config.dtype
    L = config.num_layers

    def get(name, transpose=False):
        arr = gguf.load_tensor(name).astype(np.float32)
        if transpose:
            arr = arr.T
        return arr

    params: dict = {
        "embed": jnp.asarray(get("token_embd.weight"), dt),
        "final_norm": jnp.asarray(get("output_norm.weight"), jnp.float32),
        "layers": {},
    }
    if "output.weight" in gguf.tensors:
        params["lm_head"] = jnp.asarray(get("output.weight", transpose=True), dt)

    layer_map = dict(_LAYER_MAP)
    if getattr(config, "qkv_bias", False):
        # qwen2-family GGUFs carry attention biases
        layer_map.update({
            "attn_q.bias": "bq", "attn_k.bias": "bk", "attn_v.bias": "bv",
        })
    stacks: Dict[str, List[np.ndarray]] = {v: [] for v in layer_map.values()}
    for i in range(L):
        for gname, pname in layer_map.items():
            t = get(f"blk.{i}.{gname}", transpose=gname.startswith(("attn_", "ffn_"))
                    and gname.endswith(".weight")
                    and not gname.endswith("norm.weight"))
            stacks[pname].append(t)
    for pname, arrs in stacks.items():
        stacked = np.stack(arrs)
        kind = (
            jnp.float32
            if pname.endswith("norm") or pname.startswith("b")
            else dt
        )
        params["layers"][pname] = jnp.asarray(stacked, kind)
    return params
