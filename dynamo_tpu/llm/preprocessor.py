"""OpenAI preprocessor: chat-template rendering + tokenization.

Lowers an OpenAI request into the engine-agnostic :class:`PreprocessedRequest`
(token ids, stop conditions, sampling options), and — as a pipeline operator —
maps backend outputs back into OpenAI stream chunks on the response path.

Reference parity: OpenAIPreprocessor (lib/llm/src/preprocessor.rs:64-359) and its
prompt-template formatters (preprocessor/prompt/template/{formatters,oai,tokcfg}.rs).
Chat templates are rendered with jinja2 against the HF `chat_template` from
tokenizer_config.json, with the same helper environment HF uses
(`raise_exception`, `tojson`, strftime_now).
"""

from __future__ import annotations

import asyncio
import datetime
import json
import logging
import time
from dataclasses import replace
from typing import AsyncIterator, Optional, Union

import jinja2

# mirror of engine_jax.sampling.CANDIDATES — the in-jit sampler's static
# top-k/top-p candidate budget. Mirrored (not imported) so the frontend
# process never pays a jax import; tests assert the two stay equal.
SAMPLING_CANDIDATES = 64
_TOPK_CLAMP_WARNED = False

logger = logging.getLogger(__name__)

from ..runtime.annotated import Annotated
from ..runtime.engine import AsyncEngine, Context
from ..runtime.pipeline import Operator
from .model_card import ModelDeploymentCard
from .protocols.common import (
    BackendOutput,
    FinishReason,
    HttpError,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from .protocols.openai import (
    ChatCompletionRequest,
    CompletionRequest,
    DeltaGenerator,
    Usage,
    new_request_id,
)
from .tokenizer import HFTokenizer

ANNOTATION_FORMATTED_PROMPT = "formatted_prompt"
ANNOTATION_TOKEN_IDS = "token_ids"


def _raise_exception(message: str):
    raise jinja2.TemplateError(message)


def _tojson(value, indent=None):
    return json.dumps(value, indent=indent)


def _strftime_now(fmt: str) -> str:
    return datetime.datetime.now().strftime(fmt)


class PromptFormatter:
    """Renders chat messages into a prompt string via the model's chat template."""

    def __init__(self, card: ModelDeploymentCard):
        if not card.chat_template:
            raise ValueError(f"model {card.display_name!r} has no chat template")
        env = jinja2.Environment(
            loader=jinja2.BaseLoader(),
            trim_blocks=True,
            lstrip_blocks=True,
            extensions=["jinja2.ext.loopcontrols"],
        )
        env.globals["raise_exception"] = _raise_exception
        env.globals["strftime_now"] = _strftime_now
        env.filters["tojson"] = _tojson
        self._template = env.from_string(card.chat_template)
        self._card = card

    def render(
        self,
        messages: list[dict],
        add_generation_prompt: bool = True,
        tools: Optional[list[dict]] = None,
    ) -> str:
        return self._template.render(
            messages=messages,
            add_generation_prompt=add_generation_prompt,
            bos_token=self._card.bos_token or "",
            eos_token=self._card.eos_token or "",
            tools=tools,
        )


class OpenAIPreprocessor:
    """Stateless request lowering: OpenAI request → PreprocessedRequest."""

    def __init__(self, card: ModelDeploymentCard, tokenizer: Optional[HFTokenizer] = None):
        self.card = card
        if tokenizer is None:
            if not card.tokenizer_file:
                raise ValueError(
                    f"model {card.display_name!r} has no tokenizer.json "
                    f"(searched {card.model_path!r})"
                )
            tokenizer = HFTokenizer.from_file(card.tokenizer_file)
        self.tokenizer = tokenizer
        self.formatter = PromptFormatter(card) if card.chat_template else None

    def preprocess_chat(self, request: ChatCompletionRequest) -> PreprocessedRequest:
        if self.formatter is None:
            raise ValueError("chat requests require a chat template")
        raw = request.nvext.use_raw_prompt if request.nvext else False
        if raw and request.messages:
            prompt = request.messages[-1].text_content()
        else:
            # tools render through the chat template (HF templates accept a
            # `tools` kwarg); models trained for function calling see them.
            # tool_choice "none" suppresses them for this turn.
            tools = request.tools if request.tool_choice != "none" else None
            prompt = self.formatter.render(
                [m.model_dump(exclude_none=True) for m in request.messages],
                tools=tools,
            )
        token_ids = self.tokenizer.encode(prompt)
        return self._build(request, prompt, token_ids, request.stop_list())

    def route_token_ids(self, request: dict) -> Optional[list[int]]:
        """Tokenize a raw OpenAI request dict *for KV routing only* (no stop/
        sampling lowering): chat messages are chat-template-rendered first so
        the routing prefix matches what the worker will compute. Reference:
        the Processor tokenizes frontend-side before the KV router
        (examples/llm/components/processor.py:100-160)."""
        msgs = request.get("messages")
        if msgs and self.formatter is not None:
            return self.tokenizer.encode(self.formatter.render(msgs))
        prompt = request.get("prompt")
        if isinstance(prompt, list) and prompt and isinstance(prompt[0], int):
            return [int(t) for t in prompt]
        if isinstance(prompt, list):
            prompt = "".join(prompt)
        if isinstance(prompt, str):
            return self.tokenizer.encode(prompt)
        return None

    def preprocess_completion(self, request: CompletionRequest) -> PreprocessedRequest:
        if request.suffix:
            raise HttpError(
                400, "suffix (fill-in-the-middle) is not supported by this model"
            )
        prompt = request.prompt
        if isinstance(prompt, list) and prompt and isinstance(prompt[0], int):
            token_ids = [int(t) for t in prompt]
            # echo needs the prompt as text even for token-id prompts
            prompt_text = self.tokenizer.decode(token_ids) if request.echo else None
        else:
            if isinstance(prompt, list):
                prompt = "".join(prompt)
            prompt_text = str(prompt)
            token_ids = self.tokenizer.encode(prompt_text)
        return self._build(request, prompt_text, token_ids, request.stop_list())

    def _build(
        self,
        request: Union[ChatCompletionRequest, CompletionRequest],
        prompt: Optional[str],
        token_ids: list[int],
        stops: list[str],
    ) -> PreprocessedRequest:
        ignore_eos = bool(request.nvext.ignore_eos) if request.nvext else False
        max_tokens = (
            request.effective_max_tokens()
            if isinstance(request, ChatCompletionRequest)
            else request.max_tokens
        )
        # clamp generation to the model context window
        budget = self.card.context_length - len(token_ids)
        if budget <= 0:
            raise HttpError(
                400,
                f"prompt is {len(token_ids)} tokens but the model context window "
                f"is {self.card.context_length}",
            )
        max_tokens = budget if max_tokens is None else min(max_tokens, budget)
        for name in ("frequency_penalty", "presence_penalty"):
            val = getattr(request, name, None)
            if val is not None and not -2.0 <= val <= 2.0:
                raise HttpError(
                    400, f"{name} must be within [-2, 2], got {val}"
                )
        top_k = request.top_k
        if top_k is not None and top_k > SAMPLING_CANDIDATES:
            # the in-jit sampler draws from a static top-CANDIDATES window
            # (engine_jax/sampling.py); clamp instead of silently serving a
            # different distribution than requested. Warn once — a client
            # SDK defaulting to a big top_k would otherwise spam every
            # request at WARNING level.
            global _TOPK_CLAMP_WARNED
            logger.log(
                logging.DEBUG if _TOPK_CLAMP_WARNED else logging.WARNING,
                "top_k=%d exceeds the sampler's candidate budget %d; clamping",
                top_k, SAMPLING_CANDIDATES,
            )
            _TOPK_CLAMP_WARNED = True
            top_k = SAMPLING_CANDIDATES
        pre = PreprocessedRequest(
            token_ids=token_ids,
            stop_conditions=StopConditions(
                max_tokens=max_tokens,
                stop=stops,
                ignore_eos=ignore_eos,
                min_tokens=getattr(request, "min_tokens", None),
            ),
            sampling_options=SamplingOptions(
                n=request.n,
                temperature=request.temperature,
                top_p=request.top_p,
                top_k=top_k,
                frequency_penalty=request.frequency_penalty,
                presence_penalty=request.presence_penalty,
                seed=request.seed,
                logprobs=_logprobs_request(request),
            ),
            eos_token_ids=list(self.card.eos_token_ids),
            annotations=list((request.nvext.annotations if request.nvext else None) or []),
            mdc_sum=self.card.mdcsum,
        )
        if prompt is not None:
            pre._formatted_prompt = prompt  # carried for annotations only
        return pre


def _logprobs_request(request) -> Optional[int]:
    """OpenAI request fields → engine logprobs ask (None = off).

    Chat: ``logprobs: bool`` + ``top_logprobs: int``. Completions:
    ``logprobs: int`` (number of alternatives; 0 = chosen only).
    """
    lp = getattr(request, "logprobs", None)
    if lp is None or lp is False:
        return None
    if lp is True:
        asked = int(getattr(request, "top_logprobs", None) or 0)
    else:
        asked = int(lp)  # completions style: int
    if not 0 <= asked <= 20:  # OpenAI's documented bound
        raise HttpError(400, f"top_logprobs must be within [0, 20], got {asked}")
    return asked


class ChatPreprocessorOperator(Operator):
    """Pipeline stage: OpenAI chat request → tokens forward, deltas backward.

    Forward: lower the OpenAI request via :class:`OpenAIPreprocessor` (emitting
    requested annotations). Backward: wrap detokenized :class:`BackendOutput`
    items into `chat.completion.chunk` dicts.
    Reference: OpenAIPreprocessor::into_operator (preprocessor.rs:300-359).
    """

    def __init__(self, preprocessor: OpenAIPreprocessor, chat: bool = True):
        self._pre = preprocessor
        self._chat = chat

    def _format_logprobs(self, out: BackendOutput) -> Optional[dict]:
        """BackendOutput logprobs → OpenAI wire format (chat content entries
        or the legacy completions lists). Token strings are best-effort
        single-token decodes."""
        if out.log_probs is None:
            return None
        decode = self._pre.tokenizer.decode
        tokens = out.token_ids[: len(out.log_probs)]
        if self._chat:
            entries = []
            for i, (tid, lp) in enumerate(zip(tokens, out.log_probs)):
                entry = {"token": decode([tid]), "logprob": lp}
                if out.top_logprobs is not None and i < len(out.top_logprobs):
                    entry["top_logprobs"] = [
                        {"token": decode([t]), "logprob": l}
                        for t, l in out.top_logprobs[i].items()
                    ]
                entries.append(entry)
            return {"content": entries} if entries else None
        if not tokens:
            return None
        return {
            "tokens": [decode([t]) for t in tokens],
            "token_logprobs": list(out.log_probs[: len(tokens)]),
            "top_logprobs": [
                (
                    {decode([t]): l for t, l in out.top_logprobs[i].items()}
                    if out.top_logprobs is not None and i < len(out.top_logprobs)
                    else {}
                )
                for i in range(len(tokens))
            ],
        }

    async def generate(
        self, request: Context[Union[ChatCompletionRequest, CompletionRequest]], next_engine: AsyncEngine
    ) -> AsyncIterator[Annotated[dict]]:
        oai_req = request.data
        if self._chat:
            pre = self._pre.preprocess_chat(oai_req)
        else:
            pre = self._pre.preprocess_completion(oai_req)

        # requested annotations surface as SSE events before data flows
        if ANNOTATION_FORMATTED_PROMPT in pre.annotations and getattr(pre, "_formatted_prompt", None):
            yield Annotated.from_annotation(ANNOTATION_FORMATTED_PROMPT, pre._formatted_prompt)
        if ANNOTATION_TOKEN_IDS in pre.annotations:
            yield Annotated.from_annotation(ANNOTATION_TOKEN_IDS, pre.token_ids)

        request_id = new_request_id("chatcmpl" if self._chat else "cmpl")
        gen = DeltaGenerator(request_id, oai_req.model, chat=self._chat)
        prompt_tokens = len(pre.token_ids)
        completion_tokens = 0
        include_usage = bool(
            oai_req.stream_options and oai_req.stream_options.include_usage
        )
        echo = bool(not self._chat and getattr(oai_req, "echo", None))
        n = oai_req.n or 1
        if not 1 <= n <= 32:
            raise HttpError(400, f"n must be within [1, 32], got {n}")

        # n>1: fan out n engine streams (seed-varied), multiplex by choice
        # index as they produce (reference: protocols/openai n handling; the
        # engine itself stays single-sequence). Bounded queue keeps the
        # end-to-end pull-based backpressure of the single-stream path.
        queue: asyncio.Queue = asyncio.Queue(maxsize=16)
        _DONE = object()

        def choice_request(i: int) -> PreprocessedRequest:
            if n == 1:
                return pre
            so = replace(
                pre.sampling_options,
                seed=(pre.sampling_options.seed or 0) + i if i else pre.sampling_options.seed,
            )
            return replace(pre, sampling_options=so)

        # each choice gets its OWN engine context: one choice hitting a stop
        # string must not cancel its siblings, and downstream request ids
        # (e.g. disaggregated-prefill bookkeeping) must stay distinct. Parent
        # cancellation (client disconnect) propagates to every child.
        if n == 1:
            child_ctxs = [request.transfer(pre)]
            prop_task = None
        else:
            child_ctxs = [Context(choice_request(i)) for i in range(n)]

            async def propagate_cancel():
                await request.context.stopped()
                for c in child_ctxs:
                    c.context.stop_generating()

            prop_task = asyncio.create_task(propagate_cancel())

        async def pump(i: int):
            # engine-stream exceptions must reach the caller, not die in the
            # task (a swallowed error would end the stream looking successful
            # but truncated); the main loop re-raises them
            try:
                try:
                    async for item in next_engine.generate(child_ctxs[i]):
                        await queue.put((i, item))
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    await queue.put((i, ("__raise__", e)))
            finally:
                await queue.put((i, _DONE))

        tasks = [asyncio.create_task(pump(i)) for i in range(n)]
        echoed = [not echo] * n  # per choice: prompt already emitted?
        finished = 0
        finish_count = 0
        try:
            while finished < n:
                idx, item = await queue.get()
                if item is _DONE:
                    finished += 1
                    continue
                if isinstance(item, tuple) and item and item[0] == "__raise__":
                    raise item[1]
                if isinstance(item, Annotated):
                    if item.is_error:
                        yield item
                        return
                    if item.data is None:
                        if idx == 0:
                            yield item  # pass through annotation events once
                        continue
                    out = item.data
                else:
                    out = item
                if not isinstance(out, BackendOutput):
                    raise TypeError(f"expected BackendOutput, got {type(out).__name__}")

                completion_tokens += len(out.token_ids)
                text = out.text or ""
                if not echoed[idx]:
                    echoed[idx] = True
                    text = (getattr(pre, "_formatted_prompt", None) or "") + text
                if text:
                    chunk = gen.text_chunk(
                        text, index=idx, logprobs=self._format_logprobs(out)
                    )
                    yield Annotated.from_data(chunk.model_dump(exclude_none=True), id=request.id)
                if out.finish_reason is not None:
                    finish_count += 1
                    usage = (
                        Usage(
                            prompt_tokens=prompt_tokens,
                            completion_tokens=completion_tokens,
                            total_tokens=prompt_tokens + completion_tokens,
                        )
                        if include_usage and finish_count == n
                        else None
                    )
                    chunk = gen.finish_chunk(out.finish_reason, index=idx, usage=usage)
                    yield Annotated.from_data(chunk.model_dump(exclude_none=True), id=request.id)
        finally:
            if prop_task is not None:
                prop_task.cancel()
            for t in tasks:
                t.cancel()


class DetokenizeOperator(Operator):
    """Pipeline stage: engine token-id stream → detokenized BackendOutput stream.

    Holds the per-request StopSequenceDecoder jail. Reference: Backend
    (lib/llm/src/backend.rs:63-487).
    """

    def __init__(self, card: ModelDeploymentCard, tokenizer: Optional[HFTokenizer] = None):
        self.card = card
        self.tokenizer = tokenizer or HFTokenizer.from_file(card.tokenizer_file)
        # performance attribution (runtime/profiling.py): per-token CPU of
        # incremental detokenization — the frontend-residue part the PR5
        # phase histograms couldn't see. None with DYN_TPU_PROFILE off
        # (one None-check per stream item, zero objects constructed).
        from ..runtime import profiling

        self._fcpu = (
            profiling.frontend_cpu() if profiling.enabled() else None
        )

    async def generate(
        self, request: Context[PreprocessedRequest], next_engine: AsyncEngine
    ) -> AsyncIterator[Annotated[BackendOutput]]:
        from .protocols.common import LLMEngineOutput

        pre = request.data
        stop_ids = set(pre.stop_conditions.stop_token_ids)
        if not pre.stop_conditions.ignore_eos:
            stop_ids.update(pre.eos_token_ids)
        decoder = StopSequenceDecoderFactory.create(
            self.tokenizer, pre.stop_conditions.stop, stop_ids
        )

        emitted = 0
        async for item in next_engine.generate(request):
            ann_id = item.id if isinstance(item, Annotated) else request.id
            if isinstance(item, Annotated):
                if item.is_error:
                    yield item
                    return
                if item.data is None:
                    yield item
                    continue
                out = LLMEngineOutput.from_dict(item.data) if isinstance(item.data, dict) else item.data
            else:
                out = item

            text_parts: list[str] = []
            finish: Optional[FinishReason] = out.finish_reason
            stop_hit = False
            kept_tokens: list[int] = []
            t_detok = time.perf_counter() if self._fcpu is not None else 0.0
            for tok in out.token_ids:
                decision = decoder.step(tok)
                if decision.text:
                    text_parts.append(decision.text)
                if not decision.stopped or decision.stop_token:
                    kept_tokens.append(tok)
                if decision.stopped:
                    finish = FinishReason.STOP if not decision.stop_token else FinishReason.EOS
                    stop_hit = True
                    break
            if self._fcpu is not None and out.token_ids:
                dt = time.perf_counter() - t_detok
                self._fcpu.note(
                    "detokenize", dt * 1e6, tokens=len(out.token_ids)
                )
                from ..runtime import tracing

                if tracing.enabled():
                    tracing.observe_phase("detokenize", dt)
            emitted += len(kept_tokens)

            max_t = pre.stop_conditions.max_tokens
            if finish is None and max_t is not None and emitted >= max_t:
                finish = FinishReason.LENGTH

            if finish is not None and not stop_hit:
                tail = decoder.flush()
                if tail:
                    text_parts.append(tail)

            kept = len(kept_tokens)
            yield Annotated.from_data(
                BackendOutput(
                    token_ids=kept_tokens,
                    text="".join(text_parts) or None,
                    finish_reason=finish,
                    cum_log_probs=out.cum_log_probs,
                    log_probs=(
                        out.log_probs[:kept] if out.log_probs is not None else None
                    ),
                    top_logprobs=(
                        out.top_logprobs[:kept]
                        if out.top_logprobs is not None
                        else None
                    ),
                ),
                id=ann_id,
            )
            if finish is not None:
                if out.finish_reason is None:
                    # We finished the stream (stop string / max_tokens) before
                    # the engine did: release its slot now rather than letting
                    # it decode to its own limit (ref backend.rs stop-jail
                    # semantics — the engine must observe the stop).
                    request.context.stop_generating()
                return


class StopSequenceDecoderFactory:
    @staticmethod
    def create(tokenizer: HFTokenizer, stops, stop_ids):
        from .tokenizer import StopSequenceDecoder

        return StopSequenceDecoder(
            tokenizer, stop_sequences=list(stops), stop_token_ids=list(stop_ids)
        )
