"""OpenAI preprocessor: chat-template rendering + tokenization.

Lowers an OpenAI request into the engine-agnostic :class:`PreprocessedRequest`
(token ids, stop conditions, sampling options), and — as a pipeline operator —
maps backend outputs back into OpenAI stream chunks on the response path.

Reference parity: OpenAIPreprocessor (lib/llm/src/preprocessor.rs:64-359) and its
prompt-template formatters (preprocessor/prompt/template/{formatters,oai,tokcfg}.rs).
Chat templates are rendered with jinja2 against the HF `chat_template` from
tokenizer_config.json, with the same helper environment HF uses
(`raise_exception`, `tojson`, strftime_now).
"""

from __future__ import annotations

import datetime
import json
from typing import AsyncIterator, Optional, Union

import jinja2

from ..runtime.annotated import Annotated
from ..runtime.engine import AsyncEngine, Context
from ..runtime.pipeline import Operator
from .model_card import ModelDeploymentCard
from .protocols.common import (
    BackendOutput,
    FinishReason,
    HttpError,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from .protocols.openai import (
    ChatCompletionRequest,
    CompletionRequest,
    DeltaGenerator,
    Usage,
    new_request_id,
)
from .tokenizer import HFTokenizer

ANNOTATION_FORMATTED_PROMPT = "formatted_prompt"
ANNOTATION_TOKEN_IDS = "token_ids"


def _raise_exception(message: str):
    raise jinja2.TemplateError(message)


def _tojson(value, indent=None):
    return json.dumps(value, indent=indent)


def _strftime_now(fmt: str) -> str:
    return datetime.datetime.now().strftime(fmt)


class PromptFormatter:
    """Renders chat messages into a prompt string via the model's chat template."""

    def __init__(self, card: ModelDeploymentCard):
        if not card.chat_template:
            raise ValueError(f"model {card.display_name!r} has no chat template")
        env = jinja2.Environment(
            loader=jinja2.BaseLoader(),
            trim_blocks=True,
            lstrip_blocks=True,
            extensions=["jinja2.ext.loopcontrols"],
        )
        env.globals["raise_exception"] = _raise_exception
        env.globals["strftime_now"] = _strftime_now
        env.filters["tojson"] = _tojson
        self._template = env.from_string(card.chat_template)
        self._card = card

    def render(
        self,
        messages: list[dict],
        add_generation_prompt: bool = True,
        tools: Optional[list[dict]] = None,
    ) -> str:
        return self._template.render(
            messages=messages,
            add_generation_prompt=add_generation_prompt,
            bos_token=self._card.bos_token or "",
            eos_token=self._card.eos_token or "",
            tools=tools,
        )


class OpenAIPreprocessor:
    """Stateless request lowering: OpenAI request → PreprocessedRequest."""

    def __init__(self, card: ModelDeploymentCard, tokenizer: Optional[HFTokenizer] = None):
        self.card = card
        if tokenizer is None:
            if not card.tokenizer_file:
                raise ValueError(
                    f"model {card.display_name!r} has no tokenizer.json "
                    f"(searched {card.model_path!r})"
                )
            tokenizer = HFTokenizer.from_file(card.tokenizer_file)
        self.tokenizer = tokenizer
        self.formatter = PromptFormatter(card) if card.chat_template else None

    def preprocess_chat(self, request: ChatCompletionRequest) -> PreprocessedRequest:
        if self.formatter is None:
            raise ValueError("chat requests require a chat template")
        raw = request.nvext.use_raw_prompt if request.nvext else False
        if raw and request.messages:
            prompt = request.messages[-1].text_content()
        else:
            prompt = self.formatter.render(
                [m.model_dump(exclude_none=True) for m in request.messages]
            )
        token_ids = self.tokenizer.encode(prompt)
        return self._build(request, prompt, token_ids, request.stop_list())

    def route_token_ids(self, request: dict) -> Optional[list[int]]:
        """Tokenize a raw OpenAI request dict *for KV routing only* (no stop/
        sampling lowering): chat messages are chat-template-rendered first so
        the routing prefix matches what the worker will compute. Reference:
        the Processor tokenizes frontend-side before the KV router
        (examples/llm/components/processor.py:100-160)."""
        msgs = request.get("messages")
        if msgs and self.formatter is not None:
            return self.tokenizer.encode(self.formatter.render(msgs))
        prompt = request.get("prompt")
        if isinstance(prompt, list) and prompt and isinstance(prompt[0], int):
            return [int(t) for t in prompt]
        if isinstance(prompt, list):
            prompt = "".join(prompt)
        if isinstance(prompt, str):
            return self.tokenizer.encode(prompt)
        return None

    def preprocess_completion(self, request: CompletionRequest) -> PreprocessedRequest:
        prompt = request.prompt
        if isinstance(prompt, list) and prompt and isinstance(prompt[0], int):
            token_ids = [int(t) for t in prompt]
            prompt_text = None
        else:
            if isinstance(prompt, list):
                prompt = "".join(prompt)
            prompt_text = str(prompt)
            token_ids = self.tokenizer.encode(prompt_text)
        return self._build(request, prompt_text, token_ids, request.stop_list())

    def _build(
        self,
        request: Union[ChatCompletionRequest, CompletionRequest],
        prompt: Optional[str],
        token_ids: list[int],
        stops: list[str],
    ) -> PreprocessedRequest:
        ignore_eos = bool(request.nvext.ignore_eos) if request.nvext else False
        max_tokens = (
            request.effective_max_tokens()
            if isinstance(request, ChatCompletionRequest)
            else request.max_tokens
        )
        # clamp generation to the model context window
        budget = self.card.context_length - len(token_ids)
        if budget <= 0:
            raise HttpError(
                400,
                f"prompt is {len(token_ids)} tokens but the model context window "
                f"is {self.card.context_length}",
            )
        max_tokens = budget if max_tokens is None else min(max_tokens, budget)
        pre = PreprocessedRequest(
            token_ids=token_ids,
            stop_conditions=StopConditions(
                max_tokens=max_tokens,
                stop=stops,
                ignore_eos=ignore_eos,
                min_tokens=getattr(request, "min_tokens", None),
            ),
            sampling_options=SamplingOptions(
                n=request.n,
                temperature=request.temperature,
                top_p=request.top_p,
                top_k=request.top_k,
                frequency_penalty=request.frequency_penalty,
                presence_penalty=request.presence_penalty,
                seed=request.seed,
            ),
            eos_token_ids=list(self.card.eos_token_ids),
            annotations=list((request.nvext.annotations if request.nvext else None) or []),
            mdc_sum=self.card.mdcsum,
        )
        if prompt is not None:
            pre._formatted_prompt = prompt  # carried for annotations only
        return pre


class ChatPreprocessorOperator(Operator):
    """Pipeline stage: OpenAI chat request → tokens forward, deltas backward.

    Forward: lower the OpenAI request via :class:`OpenAIPreprocessor` (emitting
    requested annotations). Backward: wrap detokenized :class:`BackendOutput`
    items into `chat.completion.chunk` dicts.
    Reference: OpenAIPreprocessor::into_operator (preprocessor.rs:300-359).
    """

    def __init__(self, preprocessor: OpenAIPreprocessor, chat: bool = True):
        self._pre = preprocessor
        self._chat = chat

    async def generate(
        self, request: Context[Union[ChatCompletionRequest, CompletionRequest]], next_engine: AsyncEngine
    ) -> AsyncIterator[Annotated[dict]]:
        oai_req = request.data
        if self._chat:
            pre = self._pre.preprocess_chat(oai_req)
        else:
            pre = self._pre.preprocess_completion(oai_req)

        # requested annotations surface as SSE events before data flows
        if ANNOTATION_FORMATTED_PROMPT in pre.annotations and getattr(pre, "_formatted_prompt", None):
            yield Annotated.from_annotation(ANNOTATION_FORMATTED_PROMPT, pre._formatted_prompt)
        if ANNOTATION_TOKEN_IDS in pre.annotations:
            yield Annotated.from_annotation(ANNOTATION_TOKEN_IDS, pre.token_ids)

        request_id = new_request_id("chatcmpl" if self._chat else "cmpl")
        gen = DeltaGenerator(request_id, oai_req.model, chat=self._chat)
        prompt_tokens = len(pre.token_ids)
        completion_tokens = 0

        include_usage = bool(
            oai_req.stream_options and oai_req.stream_options.include_usage
        )

        async for item in next_engine.generate(request.transfer(pre)):
            if isinstance(item, Annotated):
                if item.is_error:
                    yield item
                    return
                if item.data is None:
                    yield item  # pass through annotation events
                    continue
                out = item.data
            else:
                out = item
            if not isinstance(out, BackendOutput):
                raise TypeError(f"expected BackendOutput, got {type(out).__name__}")

            completion_tokens += len(out.token_ids)
            if out.text:
                chunk = gen.text_chunk(out.text)
                yield Annotated.from_data(chunk.model_dump(exclude_none=True), id=request.id)
            if out.finish_reason is not None:
                usage = (
                    Usage(
                        prompt_tokens=prompt_tokens,
                        completion_tokens=completion_tokens,
                        total_tokens=prompt_tokens + completion_tokens,
                    )
                    if include_usage
                    else None
                )
                chunk = gen.finish_chunk(out.finish_reason, usage=usage)
                yield Annotated.from_data(chunk.model_dump(exclude_none=True), id=request.id)
                return


class DetokenizeOperator(Operator):
    """Pipeline stage: engine token-id stream → detokenized BackendOutput stream.

    Holds the per-request StopSequenceDecoder jail. Reference: Backend
    (lib/llm/src/backend.rs:63-487).
    """

    def __init__(self, card: ModelDeploymentCard, tokenizer: Optional[HFTokenizer] = None):
        self.card = card
        self.tokenizer = tokenizer or HFTokenizer.from_file(card.tokenizer_file)

    async def generate(
        self, request: Context[PreprocessedRequest], next_engine: AsyncEngine
    ) -> AsyncIterator[Annotated[BackendOutput]]:
        from .protocols.common import LLMEngineOutput

        pre = request.data
        stop_ids = set(pre.stop_conditions.stop_token_ids)
        if not pre.stop_conditions.ignore_eos:
            stop_ids.update(pre.eos_token_ids)
        decoder = StopSequenceDecoderFactory.create(
            self.tokenizer, pre.stop_conditions.stop, stop_ids
        )

        emitted = 0
        async for item in next_engine.generate(request):
            ann_id = item.id if isinstance(item, Annotated) else request.id
            if isinstance(item, Annotated):
                if item.is_error:
                    yield item
                    return
                if item.data is None:
                    yield item
                    continue
                out = LLMEngineOutput.from_dict(item.data) if isinstance(item.data, dict) else item.data
            else:
                out = item

            text_parts: list[str] = []
            finish: Optional[FinishReason] = out.finish_reason
            stop_hit = False
            kept_tokens: list[int] = []
            for tok in out.token_ids:
                decision = decoder.step(tok)
                if decision.text:
                    text_parts.append(decision.text)
                if not decision.stopped or decision.stop_token:
                    kept_tokens.append(tok)
                if decision.stopped:
                    finish = FinishReason.STOP if not decision.stop_token else FinishReason.EOS
                    stop_hit = True
                    break
            emitted += len(kept_tokens)

            max_t = pre.stop_conditions.max_tokens
            if finish is None and max_t is not None and emitted >= max_t:
                finish = FinishReason.LENGTH

            if finish is not None and not stop_hit:
                tail = decoder.flush()
                if tail:
                    text_parts.append(tail)

            yield Annotated.from_data(
                BackendOutput(
                    token_ids=kept_tokens,
                    text="".join(text_parts) or None,
                    finish_reason=finish,
                    cum_log_probs=out.cum_log_probs,
                ),
                id=ann_id,
            )
            if finish is not None:
                if out.finish_reason is None:
                    # We finished the stream (stop string / max_tokens) before
                    # the engine did: release its slot now rather than letting
                    # it decode to its own limit (ref backend.rs stop-jail
                    # semantics — the engine must observe the stop).
                    request.context.stop_generating()
                return


class StopSequenceDecoderFactory:
    @staticmethod
    def create(tokenizer: HFTokenizer, stops, stop_ids):
        from .tokenizer import StopSequenceDecoder

        return StopSequenceDecoder(
            tokenizer, stop_sequences=list(stops), stop_token_ids=list(stop_ids)
        )
