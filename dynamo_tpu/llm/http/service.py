"""OpenAI-compatible HTTP frontend (aiohttp).

Routes: POST /v1/chat/completions, POST /v1/completions, GET /v1/models,
GET /metrics, GET /health, GET /live. The engine is always called streaming;
non-streaming requests fold the chunk stream through the aggregators. Client
disconnects kill the engine context.

Reference parity: HttpService/HttpServiceConfig (lib/llm/src/http/service/
service_v2.rs:24-130), handlers + monitor_for_disconnects (openai.rs:132-418).
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
import time
from typing import Optional

from aiohttp import web

from ...runtime import profiling, tracing
from ...runtime.admission import OVERLOAD_ERROR, OverloadedError
from ...runtime.annotated import Annotated
from ...runtime.engine import AsyncEngine, Context
from ...runtime.resilience import (
    DEADLINE_ERROR,
    AllInstancesFailed,
    DeadlineExceeded,
    NoHealthyInstances,
)
from ..protocols.openai import (
    ChatCompletionRequest,
    CompletionRequest,
    ModelInfo,
    ModelList,
    aggregate_chat_chunks,
    aggregate_completion_chunks,
)
from ..protocols.sse import DONE_SENTINEL, SseMessage
from .metrics import ServiceMetrics

logger = logging.getLogger(__name__)


from ..protocols.common import HttpError  # noqa: E402  (canonical home; re-exported here)


class ModelManager:
    """Registry of model name → engine, per endpoint type.

    Engines registered here speak OpenAI request in, Annotated[chunk dict] out
    (i.e. a full preprocessor→backend→worker pipeline or an in-process engine).
    Reference: ModelManager in service_v2.rs.
    """

    def __init__(self) -> None:
        self._chat: dict[str, AsyncEngine] = {}
        self._completions: dict[str, AsyncEngine] = {}

    def add_chat_model(self, name: str, engine: AsyncEngine) -> None:
        self._chat[name] = engine

    def add_completions_model(self, name: str, engine: AsyncEngine) -> None:
        self._completions[name] = engine

    def remove_chat_model(self, name: str) -> None:
        self._chat.pop(name, None)

    def remove_completions_model(self, name: str) -> None:
        self._completions.pop(name, None)

    def chat_engine(self, name: str) -> AsyncEngine:
        try:
            return self._chat[name]
        except KeyError:
            raise HttpError(404, f"model {name!r} not found") from None

    def completions_engine(self, name: str) -> AsyncEngine:
        try:
            return self._completions[name]
        except KeyError:
            raise HttpError(404, f"model {name!r} not found") from None

    def model_names(self) -> list[str]:
        return sorted(set(self._chat) | set(self._completions))

    def engines_by_model(self) -> dict[str, list[AsyncEngine]]:
        """name → engines serving it across endpoint kinds (health rollup)."""
        out: dict[str, list[AsyncEngine]] = {}
        for table in (self._chat, self._completions):
            for name, engine in table.items():
                engines = out.setdefault(name, [])
                if engine not in engines:
                    engines.append(engine)
        return out


class HttpService:
    def __init__(
        self,
        manager: Optional[ModelManager] = None,
        host: str = "0.0.0.0",
        port: int = 8080,
        metrics_prefix: str = "dynamo_frontend",
        qos=None,
    ):
        self.manager = manager or ModelManager()
        self.host = host
        self.port = port
        self.metrics = ServiceMetrics(metrics_prefix)
        # multi-tenant QoS (runtime/qos.py): tenant identity is extracted
        # here (x-tenant-id header / API-key map) and rides the engine
        # context + RPC header. The edge enforces the same token-bucket
        # rate limits the worker admission gate does, so in-process
        # engines (no RPC hop) get tenant isolation too. No DYN_TPU_TENANT_*
        # knobs ⇒ both stay None and the handler pays one None-check.
        from ...runtime import qos as qos_mod

        self.qos = qos if qos is not None else qos_mod.maybe_from_env()
        self.tenant_limiter = (
            qos_mod.TenantRateLimiter(self.qos)
            if self.qos is not None and self.qos.rate_rps > 0
            else None
        )
        self._runner: Optional[web.AppRunner] = None
        # performance attribution plane (runtime/profiling.py): with
        # DYN_TPU_PROFILE armed, the stream loop attributes per-token CPU
        # to serialize/transport-write and an event-loop lag sampler runs
        # beside the server. None/off costs one None-check per chunk (the
        # zero-overhead guard in tests/test_profiling.py).
        self._fcpu = (
            profiling.frontend_cpu() if profiling.enabled() else None
        )
        self._lag_sampler = None
        self.app = web.Application()
        self.app.add_routes(
            [
                web.post("/v1/chat/completions", self._chat_completions),
                web.post("/v1/completions", self._completions),
                web.get("/v1/models", self._models),
                web.get("/metrics", self._metrics),
                web.get("/health", self._health),
                web.get("/live", self._live),
                web.get("/debug/traces", self._debug_traces),
                web.get("/debug/slo", self._debug_slo),
                web.get("/debug/profile", self._debug_profile),
            ]
        )

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> int:
        """Start serving; returns the bound port."""
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        # resolve ephemeral port
        for sock in site._server.sockets:  # type: ignore[union-attr]
            self.port = sock.getsockname()[1]
            break
        logger.info("HTTP service listening on %s:%d", self.host, self.port)
        if self._fcpu is not None and self._lag_sampler is None:
            # event-loop lag: the direct saturation signal of a frontend
            # process (docs/observability.md §Profiling); one sampler per
            # process, shared by co-hosted services on the same loop
            self._lag_sampler = profiling.lag_sampler()
            self._lag_sampler.start()
        return self.port

    async def stop(self) -> None:
        if self._lag_sampler is not None:
            self._lag_sampler.stop()
            self._lag_sampler = None
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    async def run(self, cancel_event: Optional[asyncio.Event] = None) -> None:
        await self.start()
        try:
            if cancel_event is None:
                while True:
                    await asyncio.sleep(3600)
            else:
                await cancel_event.wait()
        finally:
            await self.stop()

    # -- handlers ----------------------------------------------------------

    async def _health(self, _request: web.Request) -> web.Response:
        """Real readiness, not a hardcoded string: per-model status derived
        from discovery + instance health. A served model with ZERO
        non-draining healthy instances makes the whole edge ``unhealthy``
        (503) so load balancers stop sending it traffic; impaired-but-
        serving models report ``degraded``. In-process engines (no
        discovery) count as healthy — process liveness is ``GET /live``."""
        overall = "healthy"
        models: dict = {}
        for name, engines in self.manager.engines_by_model().items():
            entry: dict = {"status": "healthy"}
            for engine in engines:
                summary_fn = getattr(engine, "health_summary", None)
                if summary_fn is None:
                    continue  # in-process engine: no instance plane
                summary = summary_fn()
                # SUM across a model's engines (chat vs completions may be
                # distinct clients) — a later summary must not clobber the
                # counts that justified an earlier engine's verdict
                for k in ("instances", "serving", "draining", "unhealthy",
                          "stale"):
                    entry[k] = entry.get(k, 0) + int(summary.get(k, 0))
                if summary.get("serving", 0) == 0:
                    # ANY engine with zero serving instances means some
                    # endpoint kind of this model is dead
                    entry["status"] = "unhealthy"
                elif (
                    summary.get("unhealthy", 0) or summary.get("draining", 0)
                ) and entry["status"] == "healthy":
                    entry["status"] = "degraded"
            models[name] = entry
            if entry["status"] == "unhealthy":
                overall = "unhealthy"
            elif entry["status"] == "degraded" and overall == "healthy":
                overall = "degraded"
        # control-plane view (docs/resilience.md §Control-plane blackout):
        # surfaced but NEVER a readiness failure by itself — a frontend
        # serving from stale discovery is degraded observability, not a
        # dead data plane, and load balancers must keep sending traffic
        from dynamo_tpu.runtime import control_plane

        cp = control_plane.snapshot()
        if overall == "healthy" and cp["state"] != "connected":
            overall = "degraded"
        return web.json_response(
            {"status": overall, "models": models, "control_plane": cp},
            status=503 if overall == "unhealthy" else 200,
        )

    async def _live(self, _request: web.Request) -> web.Response:
        """Pure process liveness (the container restart signal) — never
        coupled to upstream health, or a dead worker fleet would make the
        orchestrator restart a perfectly good frontend."""
        return web.json_response({"live": True})

    async def _metrics(self, _request: web.Request) -> web.Response:
        return web.Response(text=self.metrics.render(), content_type="text/plain")

    async def _debug_traces(self, request: web.Request) -> web.Response:
        """Flight-recorder export: one JSON object per line per trace
        (``?limit=N`` keeps the newest N, ``?trace_id=...`` one trace,
        ``?errored=1`` only traces with a non-ok span).
        Frontend-local spans only — worker traces come via ``llmctl trace``
        against the worker's RPC port (docs/observability.md)."""
        try:
            limit = int(request.query.get("limit", "0"))
        except ValueError:
            limit = 0
        errored = request.query.get("errored", "") not in ("", "0", "false")
        body = tracing.recorder().dump_jsonl(
            limit=limit, trace_id=request.query.get("trace_id"),
            errored=errored,
        )
        return web.Response(text=body + ("\n" if body else ""),
                            content_type="application/jsonl")

    async def _debug_profile(self, request: web.Request) -> web.Response:
        """Performance-attribution export (docs/observability.md
        §Profiling): the process's dispatch timeline summary, frontend
        per-token CPU split, and event-loop lag gauges. ``?trace=1``
        returns the same window as a Perfetto-loadable Chrome-trace JSON
        (one track per engine phase, one for the event loop);
        ``?seconds=N`` restricts to the last N seconds. Works — with
        empty sections — even when ``DYN_TPU_PROFILE`` is off, so a
        dashboard probing the wrong process gets an explicit
        ``enabled: false`` instead of a 404."""
        try:
            since = float(request.query.get("seconds", "0")) or None
        except ValueError:
            since = None
        state = profiling.dump_state(since)
        if request.query.get("trace", "") not in ("", "0", "false"):
            trace = profiling.to_chrome_trace([(
                "frontend", state.get("records", []),
                state.get("events", []),
            )])
            return web.json_response(trace)
        state.pop("records", None)  # summary view: keep the payload small
        state.pop("events", None)
        return web.json_response(state)

    async def _debug_slo(self, _request: web.Request) -> web.Response:
        """SLO / burn-rate report: the edge's own objectives (fed from the
        request metrics this process serves) plus — when a cluster
        telemetry aggregator is co-hosted — the cluster rollup and cluster
        SLOs (docs/observability.md §Cluster telemetry & SLOs)."""
        from ...runtime import telemetry

        return web.json_response(telemetry.dump_state())

    async def _models(self, _request: web.Request) -> web.Response:
        listing = ModelList(data=[ModelInfo(id=n) for n in self.manager.model_names()])
        return web.json_response(listing.model_dump())

    async def _chat_completions(self, request: web.Request) -> web.StreamResponse:
        return await self._handle_openai(request, chat=True)

    async def _completions(self, request: web.Request) -> web.StreamResponse:
        return await self._handle_openai(request, chat=False)

    async def _handle_openai(self, request: web.Request, chat: bool) -> web.StreamResponse:
        endpoint = "chat/completions" if chat else "completions"
        try:
            body = await request.json()
        except (json.JSONDecodeError, UnicodeDecodeError):
            return _error_response(400, "invalid JSON body")

        try:
            oai_req = (
                ChatCompletionRequest.model_validate(body)
                if chat
                else CompletionRequest.model_validate(body)
            )
        except Exception as e:  # pydantic.ValidationError
            return _error_response(400, f"invalid request: {e}")

        try:
            engine = (
                self.manager.chat_engine(oai_req.model)
                if chat
                else self.manager.completions_engine(oai_req.model)
            )
        except HttpError as e:
            return _error_response(e.status, e.message)

        streaming = bool(oai_req.stream)
        ctx = Context(oai_req)
        # tenant identity (docs/qos.md): the AUTHENTICATED API-key binding
        # wins over the client-supplied x-tenant-id header (a spoofed
        # header must not bill another tenant's quota), undeclared ids
        # optionally collapse into the default tenant
        # (DYN_TPU_TENANT_UNMAPPED=shared), and anonymous traffic becomes
        # the shared default tenant — it must not bypass the rate gates.
        # With QoS off, a bare header still rides the context for tracing.
        tenant = request.headers.get("x-tenant-id")
        tenant_class = None
        if self.qos is not None:
            tenant = self.qos.resolve_tenant(
                tenant, request.headers.get("authorization")
            )
            if tenant:
                # bounded-cardinality CLASS (never the raw id) labels the
                # per-tenant SLO rows on /debug/slo (docs/qos.md)
                tenant_class = self.qos.class_name_of(tenant)
        if tenant:
            ctx.context.tenant = tenant
        if self.tenant_limiter is not None:
            wait_s = self.tenant_limiter.take(tenant)
            if wait_s > 0:
                # per-tenant 429 before any engine work: the Retry-After
                # is THIS tenant's bucket refill, not a global hint
                with self.metrics.inflight_guard(
                    oai_req.model, endpoint,
                    "stream" if streaming else "unary",
                    tenant_class=tenant_class,
                ) as g:
                    g.mark_shed()
                    return _overloaded_response(
                        f"{OVERLOAD_ERROR}: tenant {tenant!r} over rate quota",
                        # same 60 s cap as the worker gate: one policy
                        # knob must yield one client backoff contract
                        # wherever the request is shed
                        retry_after_ms=min(int(wait_s * 1000) + 1, 60_000),
                    )
        # edge span: the trace's root for locally-originated requests, or a
        # child of the caller's context when an (optional) W3C traceparent
        # header arrives — malformed headers just start a fresh root. The
        # span rides ctx.context.trace into the engine/router layers; the
        # contextvars make every log line in this handler carry the ids.
        attrs = {"model": oai_req.model, "endpoint": endpoint,
                 "stream": streaming, "request_id": ctx.id}
        if tenant:
            attrs["tenant"] = tenant
        edge = tracing.start_span(
            "http.edge",
            parent=tracing.parse_traceparent(request.headers.get("traceparent")),
            attributes=attrs,
        )
        tokens = None
        if edge is not None:
            ctx.context.trace = edge
            tokens = (tracing.set_current(edge), tracing.set_request_id(ctx.id))
        guard = self.metrics.inflight_guard(
            oai_req.model, endpoint, "stream" if streaming else "unary",
            tenant_class=tenant_class,
        )
        try:
            with guard:
                if streaming:
                    return await self._stream_response(request, engine, ctx, guard, chat)
                return await self._unary_response(engine, ctx, guard, chat)
        finally:
            if edge is not None:
                edge.end(_EDGE_STATUS.get(guard.status, guard.status))
            if tokens is not None:
                tracing.reset_current(tokens[0])
                tracing.reset_request_id(tokens[1])

    async def _stream_response(
        self,
        request: web.Request,
        engine: AsyncEngine,
        ctx: Context,
        guard,
        chat: bool,
    ) -> web.StreamResponse:
        # pull the first item BEFORE sending headers, so validation errors
        # (e.g. over-length prompts) still surface as proper HTTP status codes
        stream = engine.generate(ctx)
        if hasattr(stream, "__await__"):
            stream = await stream
        it = stream.__aiter__()
        try:
            first_item = await it.__anext__()
        except StopAsyncIteration:
            first_item = None
        except HttpError as e:
            return _error_response(e.status, e.message)
        except DeadlineExceeded as e:
            return _error_response(504, str(e) or DEADLINE_ERROR)
        except OverloadedError as e:
            guard.mark_shed()
            return _overloaded_response(str(e), e.retry_after_ms)
        except (NoHealthyInstances, AllInstancesFailed, ConnectionError, OSError) as e:
            return _error_response(502, f"upstream failure: {e}")

        # an upstream that failed before producing anything is an HTTP error,
        # not a 200 stream carrying an error payload
        if (
            isinstance(first_item, Annotated)
            and first_item.is_error
        ):
            msg = first_item.error_message() or "upstream failure"
            status = _upstream_status(msg)
            if status == 429:
                guard.mark_shed()
                return _overloaded_response(msg)
            return _error_response(status, msg)

        resp = web.StreamResponse(
            status=200,
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "Connection": "keep-alive",
            },
        )
        await resp.prepare(request)

        async def _rest():
            if first_item is not None:
                yield first_item
            async for i in it:
                yield i

        tmpl = _SseTemplate()
        envelope: Optional[dict] = None  # id/object/created/model of the stream
        # mid-stream resume visibility (docs/resilience.md): the routing
        # client's journal rides the SAME EngineContext; when its resume
        # count grows, attribute the next first-chunk wait to inter_token
        # instead of TTFT. None on non-resumable paths = one check total.
        journal = getattr(ctx.context, "journal", None)
        seen_resumes = 0
        try:
            async for item in _rest():
                seen_resumes = guard.sync_resumes(journal, seen_resumes)
                if isinstance(item, Annotated):
                    if item.is_error:
                        # headers already sent: error goes in-band, followed
                        # by a WELL-FORMED final chunk (finish_reason
                        # "error") + [DONE] so clients aren't left dangling
                        msg = SseMessage(event="error", data=json.dumps({"message": item.error_message()}))
                        await resp.write((msg.encode() + "\n\n").encode())
                        await _write_error_finish(resp, envelope, chat)
                        break
                    if item.data is None:
                        # annotation/comment event
                        await resp.write((SseMessage.from_annotated(item).encode() + "\n\n").encode())
                        continue
                    payload = item.data
                else:
                    payload = item
                if isinstance(payload, dict) and envelope is None:
                    envelope = {
                        k: payload[k]
                        for k in ("id", "object", "created", "model")
                        if k in payload
                    }
                has_content = _chunk_has_content(payload)
                if has_content:
                    guard.mark_chunk()  # TTFT on first, inter-token gap after
                    guard.count_tokens()
                if self._fcpu is None:
                    fast = tmpl.encode(payload)
                    if fast is not None:
                        await resp.write(fast)
                    else:
                        await resp.write((f"data: {json.dumps(payload)}\n\n").encode())
                else:
                    # per-token CPU attribution (profiling plane): split
                    # the SSE hot path into serialize vs transport-write
                    # so the µs/token residue decomposes
                    t0 = time.perf_counter()
                    data = tmpl.encode(payload)
                    if data is None:
                        data = (f"data: {json.dumps(payload)}\n\n").encode()
                    t1 = time.perf_counter()
                    await resp.write(data)
                    t2 = time.perf_counter()
                    self._fcpu.note(
                        "serialize", (t1 - t0) * 1e6,
                        tokens=1 if has_content else 0,
                    )
                    self._fcpu.note(
                        "transport_write", (t2 - t1) * 1e6,
                        tokens=1 if has_content else 0,
                    )
                    if tracing.enabled():
                        tracing.observe_phase("serialize", t1 - t0)
            else:
                guard.mark_ok()
            await resp.write(f"data: {DONE_SENTINEL}\n\n".encode())
        except (ConnectionResetError, asyncio.CancelledError):
            # client went away: kill the engine context so the worker stops
            ctx.context.kill()
            logger.info("client disconnected, killed request %s", ctx.id)
            raise
        except Exception as e:  # headers already sent: error must go in-band
            logger.exception("engine error mid-stream for request %s", ctx.id)
            ctx.context.kill()
            msg = SseMessage(event="error", data=json.dumps({"message": str(e)}))
            with contextlib.suppress(ConnectionError):
                await resp.write((msg.encode() + "\n\n").encode())
                await _write_error_finish(resp, envelope, chat)
                await resp.write(f"data: {DONE_SENTINEL}\n\n".encode())
        finally:
            with contextlib.suppress(ConnectionError):
                await resp.write_eof()
        return resp

    async def _unary_response(
        self, engine: AsyncEngine, ctx: Context, guard, chat: bool
    ) -> web.Response:
        chunks: list[dict] = []
        n_tokens = 0
        seen_resumes = 0
        try:
            async for item in engine.generate(ctx):
                seen_resumes = guard.sync_resumes(
                    getattr(ctx.context, "journal", None), seen_resumes
                )
                if isinstance(item, Annotated):
                    if item.is_error:
                        msg = item.error_message() or "engine error"
                        if not chunks:
                            # upstream failed before producing anything:
                            # 429/502/504, not a generic server error
                            status = _upstream_status(msg)
                            if status == 429:
                                guard.mark_shed()
                                return _overloaded_response(msg)
                            return _error_response(status, msg)
                        return _error_response(500, msg)
                    if item.data is None:
                        continue
                    chunks.append(item.data)
                else:
                    chunks.append(item)
                if _chunk_has_content(chunks[-1]):
                    guard.mark_first_token()
                    n_tokens += 1
        except HttpError as e:
            return _error_response(e.status, e.message)
        except DeadlineExceeded as e:
            return _error_response(504, str(e) or DEADLINE_ERROR)
        except OverloadedError as e:
            guard.mark_shed()
            return _overloaded_response(str(e), e.retry_after_ms)
        except (NoHealthyInstances, AllInstancesFailed, ConnectionError, OSError) as e:
            return _error_response(502, f"upstream failure: {e}")
        if not chunks:
            return _error_response(500, "engine produced no response")
        full = aggregate_chat_chunks(chunks) if chat else aggregate_completion_chunks(chunks)
        if (
            chat
            and getattr(ctx.data, "tools", None)
            and getattr(ctx.data, "tool_choice", None) != "none"
        ):
            _extract_tool_calls(full)
        guard.mark_ok()
        guard.count_tokens(n_tokens)
        return web.json_response(full.model_dump(exclude_none=True))


# InflightGuard status label → edge-span terminal status (the recorder pins
# "overloaded"/"error"; plain "success" maps to the span-model "ok")
_EDGE_STATUS = {"success": "ok", "overloaded": "overloaded", "error": "error"}


def _extract_tool_calls(full) -> None:
    """Best-effort function-call detection on a folded chat response.

    When the request carried ``tools`` and the model answered with a bare
    JSON object of the common ``{"name": ..., "arguments"|"parameters": ...}``
    shape (the format llama-3/qwen-style templates train), surface it as an
    OpenAI ``tool_calls`` entry with finish_reason "tool_calls". Models whose
    templates emit other wrappers stream through as plain text (parity with
    the reference, which delegates parsing to its engines).
    """
    import uuid as _uuid

    for choice in full.choices:
        content = choice.message.content
        if not content:
            continue
        text = content.strip()
        if not (text.startswith("{") and text.endswith("}")):
            continue
        try:
            obj = json.loads(text)
        except ValueError:
            continue
        if not isinstance(obj, dict) or "name" not in obj:
            continue
        args = obj.get("arguments", obj.get("parameters"))
        if args is None:
            continue
        choice.message.tool_calls = [
            {
                "id": f"call_{_uuid.uuid4().hex[:24]}",
                "type": "function",
                "function": {
                    "name": obj["name"],
                    "arguments": json.dumps(args) if not isinstance(args, str) else args,
                },
            }
        ]
        choice.message.content = None
        choice.finish_reason = "tool_calls"


def _chunk_has_content(payload) -> bool:
    """True if this chunk carries generated content (a token), not just a
    role/finish frame — keeps output-token metrics and TTFT honest."""
    if not isinstance(payload, dict):
        return False
    for choice in payload.get("choices", []):
        if (choice.get("delta") or {}).get("content"):
            return True
        if choice.get("text"):
            return True
    return False


class _SseTemplate:
    """Per-request fast path for the dominant SSE frame shape.

    Every streamed chat/completions chunk in a request differs ONLY in the
    token text: id/object/created/model repeat verbatim. json.dumps of the
    nested dict is the measured frontend hot spot (VERDICT r4 item 6 —
    24.5 µs/token at saturation, one frontend per ~7 chips); splicing the
    escaped token into a pre-encoded prefix/suffix removes the per-token
    tree walk. Any chunk that doesn't match the plain content-delta shape
    (logprobs, finish frames, tool calls, n>1) falls back to json.dumps —
    byte-identical output either way (templates are built FROM a dumps of
    the first matching chunk)."""

    __slots__ = ("prefix", "suffix", "key")

    def __init__(self):
        self.prefix: Optional[bytes] = None
        self.suffix: Optional[bytes] = None
        self.key = None

    _MARK = "@DYN_TPU_TOK@"

    def encode(self, payload) -> Optional[bytes]:
        try:
            # unknown top-level fields (usage from a custom engine, ...)
            # would be frozen into the template: fall back on anything
            # beyond the standard chunk envelope
            if set(payload) - {"id", "object", "created", "model", "choices"}:
                return None
            choices = payload["choices"]
            if len(choices) != 1:
                return None
            ch = choices[0]
            if ch.get("finish_reason") is not None or ch.get("logprobs"):
                return None
            delta = ch.get("delta")
            if delta is not None:
                if set(ch) - {"index", "delta", "finish_reason", "logprobs"}:
                    return None
                if set(delta) != {"content"} or not isinstance(
                    delta["content"], str
                ):
                    return None
                tok = delta["content"]
            else:
                if set(ch) - {"index", "text", "finish_reason", "logprobs"} \
                        or not isinstance(ch.get("text"), str):
                    return None
                tok = ch["text"]
            # the choice index is IN the key: n>1 requests stream as
            # interleaved single-choice chunks with identical id/created —
            # without it, choice 1's tokens would reuse choice 0's template
            key = (
                payload.get("id"), payload.get("created"),
                ch.get("index"), delta is None,
            )
        except (TypeError, KeyError, AttributeError):
            return None
        if key != self.key or self.prefix is None:
            # build the template from a real dumps of THIS chunk with a
            # marker token — output stays byte-identical to the slow path
            probe = json.loads(json.dumps(payload))
            if delta is not None:
                probe["choices"][0]["delta"]["content"] = self._MARK
            else:
                probe["choices"][0]["text"] = self._MARK
            enc = json.dumps(probe)
            mark = json.dumps(self._MARK)[1:-1]
            i = enc.find(mark)
            if i < 0:
                return None
            self.prefix = ("data: " + enc[:i]).encode()
            self.suffix = (enc[i + len(mark):] + "\n\n").encode()
            self.key = key
        # token text goes through the same escaping rules as dumps
        return self.prefix + json.dumps(tok)[1:-1].encode() + self.suffix


def _upstream_status(message: str) -> int:
    """Pre-first-token upstream failures: 504 when the request's deadline
    expired, 429 when every instance shed it as overloaded (the canonical
    message prefixes cross process boundaries in the error envelope), 502
    for everything else upstream."""
    if message.startswith(DEADLINE_ERROR):
        return 504
    if message.startswith(OVERLOAD_ERROR):
        return 429
    return 502


def _overloaded_response(message: str, retry_after_ms: int = 0) -> web.Response:
    """429 with ``Retry-After`` (whole seconds, minimum 1) and an
    OpenAI-error-schema body: overload is the one upstream failure where
    the right client behavior is *back off and retry the same edge*, so it
    gets its own status + hint instead of the generic 502."""
    retry_after_s = max(1, -(-int(retry_after_ms) // 1000)) if retry_after_ms else 1
    return web.json_response(
        {
            "error": {
                "message": message,
                "type": "overloaded_error",
                "param": None,
                "code": "overloaded",
            }
        },
        status=429,
        headers={"Retry-After": str(retry_after_s)},
    )


async def _write_error_finish(resp: web.StreamResponse, envelope: Optional[dict],
                              chat: bool) -> None:
    """Emit a well-formed final SSE chunk with ``finish_reason: "error"`` so
    streaming clients see a terminated choice instead of a dangling stream."""
    chunk: dict = dict(envelope or {})
    choice: dict = {"index": 0, "finish_reason": "error"}
    if chat:
        choice["delta"] = {}
    else:
        choice["text"] = ""
    chunk["choices"] = [choice]
    await resp.write((f"data: {json.dumps(chunk)}\n\n").encode())


def _error_response(status: int, message: str) -> web.Response:
    return web.json_response(
        {"error": {"message": message, "type": "invalid_request_error" if status < 500 else "internal_error"}},
        status=status,
    )
