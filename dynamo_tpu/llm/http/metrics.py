"""Prometheus-format HTTP service metrics (no external deps).

Counters by model/endpoint/type/status, an inflight gauge, request-duration
+ TTFT + inter-token-latency histograms, with an RAII-style InflightGuard.
This module also owns the ONE label-escaping/formatting helper pair
(:func:`escape_label`, :func:`fmt_labels`) every Prometheus renderer in the
project shares (``components/metrics.py`` included) — duplicated escaping
logic drifted once already.
Reference parity: lib/llm/src/http/service/metrics.rs:36-346.
"""

from __future__ import annotations

import math
import threading
import time
from collections import defaultdict
from typing import Iterable, Optional

DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

# inter-token gaps sit well under the request-duration buckets: a healthy
# decode emits every few ms, and the interesting tail is 100 ms-ish stalls
ITL_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
)


def escape_label(v: str) -> str:
    """Escape a Prometheus text-format label value (backslash, quote,
    newline) — an id containing any of these would otherwise corrupt the
    whole /metrics exposition."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def fmt_labels(labels: dict[str, str]) -> str:
    """``{a="x",b="y"}`` with values escaped; empty string for no labels."""
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{escape_label(str(v))}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


# historical private name, kept so in-flight callers keep working
_fmt_labels = fmt_labels


def _observe_trace_phase(phase: str, seconds: float) -> None:
    """Feed an edge-measured phase sample into the tracing plane's shared
    phase histogram. Lazy import + enabled() gate: this module must stay
    importable without the runtime tree, and with tracing disabled the
    streaming hot path must not pay for phase bookkeeping."""
    try:
        from dynamo_tpu.runtime import tracing
    except Exception:  # pragma: no cover - runtime tree absent
        return
    if tracing.enabled():
        tracing.observe_phase(phase, seconds)


def _observe_slo_latency(
    series: str, model: str, seconds: float, tenant: Optional[str] = None
) -> None:
    """Feed an edge latency sample (TTFT / inter-token) into the telemetry
    plane's SLO store. Same lazy-import + enabled() discipline as the
    tracing feed: ``DYN_TPU_SLO=0`` costs one boolean check. With a tenant
    class attached (QoS on, docs/qos.md) a SECOND, tenant-labeled series
    gets the sample — the SLO engine fans out over every label set it has
    seen, so per-tenant-class ``ttft_p95``/``itl_p95`` rows appear on
    ``/debug/slo`` without touching the model-level objective."""
    try:
        from dynamo_tpu.runtime import telemetry
    except Exception:  # pragma: no cover - runtime tree absent
        return
    telemetry.observe_latency(series, seconds * 1e3, model=model)
    if tenant:
        telemetry.observe_latency(
            series, seconds * 1e3, model=model, tenant=tenant
        )


def _count_slo_request(outcome: str, model: str) -> None:
    """One finished edge request into the SLO store (error-rate and
    overload-share objectives)."""
    try:
        from dynamo_tpu.runtime import telemetry
    except Exception:  # pragma: no cover - runtime tree absent
        return
    telemetry.count_request(outcome, model=model)


class Counter:
    def __init__(self, name: str, help_: str, label_names: tuple[str, ...] = ()):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self._values: dict[tuple, float] = defaultdict(float)
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = tuple(str(labels.get(n, "")) for n in self.label_names)
        with self._lock:
            self._values[key] += amount

    def render(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} counter"
        with self._lock:
            items = list(self._values.items())
        if not items and not self.label_names:
            items = [((), 0.0)]
        for key, val in items:
            labels = dict(zip(self.label_names, key))
            yield f"{self.name}{_fmt_labels(labels)} {val:g}"


class Gauge:
    def __init__(self, name: str, help_: str, label_names: tuple[str, ...] = ()):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self._values: dict[tuple, float] = defaultdict(float)
        self._lock = threading.Lock()

    def set(self, value: float, **labels: str) -> None:
        key = tuple(str(labels.get(n, "")) for n in self.label_names)
        with self._lock:
            self._values[key] = value

    def add(self, amount: float, **labels: str) -> None:
        key = tuple(str(labels.get(n, "")) for n in self.label_names)
        with self._lock:
            self._values[key] += amount

    def get(self, **labels: str) -> float:
        key = tuple(str(labels.get(n, "")) for n in self.label_names)
        with self._lock:
            return self._values.get(key, 0.0)

    def render(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} gauge"
        with self._lock:
            items = list(self._values.items())
        if not items and not self.label_names:
            items = [((), 0.0)]
        for key, val in items:
            labels = dict(zip(self.label_names, key))
            yield f"{self.name}{_fmt_labels(labels)} {val:g}"


class Histogram:
    def __init__(
        self,
        name: str,
        help_: str,
        label_names: tuple[str, ...] = (),
        buckets: tuple[float, ...] = DEFAULT_BUCKETS,
    ):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self.buckets = tuple(buckets) + (math.inf,)
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = defaultdict(float)
        self._totals: dict[tuple, int] = defaultdict(int)
        self._lock = threading.Lock()

    def observe(self, value: float, **labels: str) -> None:
        key = tuple(str(labels.get(n, "")) for n in self.label_names)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            self._sums[key] += value
            self._totals[key] += 1

    def render(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} histogram"
        with self._lock:
            keys = list(self._counts.keys())
            for key in keys:
                labels = dict(zip(self.label_names, key))
                for i, b in enumerate(self.buckets):
                    le = "+Inf" if math.isinf(b) else f"{b:g}"
                    bl = dict(labels, le=le)
                    yield f"{self.name}_bucket{_fmt_labels(bl)} {self._counts[key][i]}"
                yield f"{self.name}_sum{_fmt_labels(labels)} {self._sums[key]:g}"
                yield f"{self.name}_count{_fmt_labels(labels)} {self._totals[key]}"

    def snapshot(self) -> dict[tuple, tuple[list[int], int, float]]:
        """{label_values: (cumulative_bucket_counts, total, sum)} — the raw
        state quantile estimators (tracing.phase_summary, bench.py) read."""
        with self._lock:
            return {
                key: (list(counts), self._totals[key], self._sums[key])
                for key, counts in self._counts.items()
            }


class Registry:
    def __init__(self) -> None:
        self._metrics: list = []
        self._lock = threading.Lock()

    def register(self, metric):
        with self._lock:
            self._metrics.append(metric)
        return metric

    def render(self) -> str:
        lines: list[str] = []
        with self._lock:
            metrics = list(self._metrics)
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


class ServiceMetrics:
    """The HTTP service metric set (reference: Metrics::new(prefix))."""

    LABELS = ("model", "endpoint", "request_type", "status")

    def __init__(self, prefix: str = "dynamo_frontend"):
        self.registry = Registry()
        self.requests = self.registry.register(
            Counter(f"{prefix}_requests_total", "Total LLM requests", self.LABELS)
        )
        self.inflight = self.registry.register(
            Gauge(f"{prefix}_inflight_requests", "Concurrent in-flight requests", ("model",))
        )
        self.duration = self.registry.register(
            Histogram(f"{prefix}_request_duration_seconds", "Request duration", ("model",))
        )
        self.output_tokens = self.registry.register(
            Counter(f"{prefix}_output_tokens_total", "Streamed output tokens", ("model",))
        )
        self.ttft = self.registry.register(
            Histogram(
                f"{prefix}_time_to_first_token_seconds",
                "Time to first streamed SSE chunk with content",
                ("model",),
            )
        )
        self.itl = self.registry.register(
            Histogram(
                f"{prefix}_inter_token_latency_seconds",
                "Gap between consecutive streamed content chunks",
                ("model",),
                buckets=ITL_BUCKETS,
            )
        )
        self.overloaded = self.registry.register(
            Counter(
                f"{prefix}_overloaded_total",
                "Requests shed with 429 + Retry-After (upstream overload)",
                ("model",),
            )
        )
        self.resumed = self.registry.register(
            Counter(
                f"{prefix}_resume_total",
                "Streams resumed on another worker after a mid-decode death",
                ("model",),
            )
        )
        self.migrated = self.registry.register(
            Counter(
                f"{prefix}_migrations_total",
                "Streams live-migrated off a draining worker mid-decode",
                ("model",),
            )
        )

    def inflight_guard(
        self, model: str, endpoint: str, request_type: str,
        tenant_class: Optional[str] = None,
    ) -> "InflightGuard":
        return InflightGuard(self, model, endpoint, request_type,
                             tenant_class=tenant_class)

    def render(self) -> str:
        # the phase-latency histogram (runtime/tracing.py) rides the same
        # exposition: one scrape shows edge metrics AND per-phase latency
        # of whatever spans this process recorded (lazy import — metrics
        # must stay importable without the runtime tree)
        out = self.registry.render()
        try:
            from dynamo_tpu.runtime import tracing

            out += tracing.render_phase_metrics()
        except Exception:  # tracing unavailable must never break /metrics
            pass
        try:
            from dynamo_tpu.runtime import telemetry

            # process identity + uptime, and the cluster section when a
            # telemetry aggregator is co-hosted with this frontend
            out += telemetry.render_process_info()
            out += telemetry.render_cluster_metrics()
        except Exception:  # telemetry unavailable must never break /metrics
            pass
        try:
            from dynamo_tpu.runtime import control_plane

            # statestore/bus connectivity as this process sees it
            # (docs/resilience.md §Control-plane blackout)
            out += control_plane.render_prometheus()
        except Exception:  # must never break /metrics
            pass
        try:
            from dynamo_tpu.runtime import profiling

            # frontend hot-path attribution (docs/observability.md
            # §Profiling): per-token CPU split + event-loop lag gauges —
            # empty string until the profiling plane recorded anything
            out += profiling.render_frontend_prometheus()
        except Exception:  # must never break /metrics
            pass
        return out


class InflightGuard:
    """Context manager: inflight gauge up/down + request counter + duration.

    Reference: InflightGuard RAII (http/service/metrics.rs).
    """

    def __init__(self, metrics: ServiceMetrics, model: str, endpoint: str,
                 request_type: str, tenant_class: Optional[str] = None):
        self._m = metrics
        self.model = model
        self.endpoint = endpoint
        self.request_type = request_type
        # tenant CLASS (bounded cardinality — never the raw tenant id) for
        # per-class SLO rows; None on single-tenant edges = zero extra work
        self.tenant_class = tenant_class
        self.status = "error"
        self._start: Optional[float] = None
        self._first_token_at: Optional[float] = None
        self._last_chunk_at: Optional[float] = None
        self._resumed = False
        # per-kind watermarks for sync_resumes (resume vs live migration)
        self._seen_resumes = 0
        self._seen_migrations = 0

    def __enter__(self) -> "InflightGuard":
        self._start = time.perf_counter()
        self._m.inflight.add(1, model=self.model)
        return self

    def mark_ok(self) -> None:
        self.status = "success"

    def mark_shed(self) -> None:
        """Request answered 429 (overload shed): its own status label + a
        dedicated counter, so dashboards can tell deliberate load shedding
        from actual failures."""
        self.status = "overloaded"
        self._m.overloaded.inc(1, model=self.model)

    def sync_resumes(self, journal, seen: int) -> int:
        """Fold any NEW recoveries recorded on the request's resume journal
        (``EngineContext.journal``) into this guard: one :meth:`mark_resume`
        per resume — and one :meth:`mark_migration` per live migration —
        since ``seen``. Both re-home kinds attribute the next first-chunk
        wait to ITL, never TTFT. Returns the new watermark (resumes +
        migrations); None journal (non-resumable request) is a no-op.
        Shared by the streaming and unary HTTP loops so the two can't
        drift."""
        if journal is None:
            return seen
        resumes = journal.resumes
        migrations = getattr(journal, "migrations", 0)
        # the guard is per-request: each kind keeps its own internal
        # watermark, so interleaved resume/migration sequences attribute
        # every event to the right counter
        while self._seen_resumes < resumes:
            self._seen_resumes += 1
            self.mark_resume()
        while self._seen_migrations < migrations:
            self._seen_migrations += 1
            self.mark_migration()
        return resumes + migrations

    def mark_migration(self) -> None:
        """The upstream stream was live-migrated off a draining worker
        (``EngineContext.journal`` grew its migration count). Same ITL
        attribution as :meth:`mark_resume` — the gap is a planned re-home,
        not an admission wait — with its own frontend counter."""
        self._resumed = True
        self._m.migrated.inc(1, model=self.model)

    def mark_resume(self) -> None:
        """The upstream stream was resumed on another worker
        (``EngineContext.journal`` grew its resume count). Counts once per
        resume into the frontend resume counter; if no content chunk has
        been delivered yet, the eventual first-chunk latency is attributed
        to ``inter_token``/``itl_ms`` instead of TTFT — the wait was a
        mid-decode recovery gap, not an admission wait, and letting it into
        ``ttft_p95`` would page admission capacity alarms for worker
        deaths that were fully absorbed."""
        self._resumed = True
        self._m.resumed.inc(1, model=self.model)

    def mark_first_token(self) -> None:
        if self._first_token_at is None and self._start is not None:
            self._first_token_at = time.perf_counter()
            if not self._resumed:
                self._m.ttft.observe(
                    self._first_token_at - self._start, model=self.model
                )

    def mark_chunk(self) -> None:
        """Streaming path: called once per content-bearing SSE chunk.
        First chunk observes TTFT; every later one observes the gap since
        the previous chunk (the frontend's inter-token latency). Both also
        feed the shared phase-latency histogram (``ttft``/``inter_token``
        phases) when tracing is enabled. A first chunk that arrived after
        a mid-stream resume is an inter-token gap, not a TTFT (see
        :meth:`mark_resume`) — the pause stays visible, in the right
        series."""
        now = time.perf_counter()
        if self._first_token_at is None:
            self.mark_first_token()
            if self._first_token_at is not None and self._start is not None:
                ttft = self._first_token_at - self._start
                if self._resumed:
                    self._m.itl.observe(ttft, model=self.model)
                    _observe_trace_phase("inter_token", ttft)
                    _observe_slo_latency("itl_ms", self.model, ttft,
                                         tenant=self.tenant_class)
                else:
                    _observe_trace_phase("ttft", ttft)
                    _observe_slo_latency("ttft_ms", self.model, ttft,
                                         tenant=self.tenant_class)
        elif self._last_chunk_at is not None:
            gap = now - self._last_chunk_at
            self._m.itl.observe(gap, model=self.model)
            _observe_trace_phase("inter_token", gap)
            _observe_slo_latency("itl_ms", self.model, gap,
                                 tenant=self.tenant_class)
        self._last_chunk_at = now

    def count_tokens(self, n: int = 1) -> None:
        self._m.output_tokens.inc(n, model=self.model)

    def __exit__(self, exc_type, exc, tb) -> None:
        self._m.inflight.add(-1, model=self.model)
        if self._start is not None:
            self._m.duration.observe(time.perf_counter() - self._start, model=self.model)
        status = self.status if exc_type is None else "error"
        self._m.requests.inc(
            1,
            model=self.model,
            endpoint=self.endpoint,
            request_type=self.request_type,
            status=status,
        )
        _count_slo_request(status, self.model)
