"""Frontend model discovery: keep the ModelManager in sync with the registry.

Workers register ``{ns}/models/{kind}/{name}`` entries (lease-attached) when
they serve an endpoint; ``llmctl`` writes the same entries by hand. The
frontend watches the prefix and adds/removes models live — a worker started
AFTER the frontend appears without a restart, and a dead worker's lease
expiry removes its model.

Re-designed from the reference's etcd watcher
(`lib/llm/src/http/service/discovery.rs:38-171`, consumed by
`components/http/src/main.rs:50-104`): same key layout and lifecycle, but
the client pipeline is this framework's direct-dial EndpointClient instead
of a NATS push router.
"""

from __future__ import annotations

import asyncio
import json
import logging
import uuid
from typing import Dict, Optional

from dynamo_tpu.llm.http.service import ModelManager
from dynamo_tpu.runtime import control_plane

logger = logging.getLogger(__name__)


class ModelWatcher:
    """Watches ``{namespace}/models/`` and maintains manager + clients."""

    def __init__(
        self,
        drt,
        namespace: str,
        manager: ModelManager,
        router_mode: str = "round_robin",
        kv_block_size: int = 16,
        policy=None,
    ):
        from dynamo_tpu.runtime.resilience import ResiliencePolicy

        self.drt = drt
        self.namespace = namespace
        self.manager = manager
        self.router_mode = router_mode
        self.kv_block_size = kv_block_size
        # one resilience policy shared by every discovered model's client;
        # defaults come from the environment so operators can tune the
        # frontend's failover/deadline behavior without code changes
        self.policy = policy or ResiliencePolicy.from_env()
        # entries are per-worker-instance ({kind}/{name}:{instance}); a model
        # is served by ONE client per (kind, name) and removed only when its
        # last entry disappears
        self._entry_model: Dict[str, tuple] = {}  # key → (kind, name)
        self._model_keys: Dict[tuple, set] = {}  # (kind, name) → entry keys
        self._clients: Dict[tuple, object] = {}  # (kind, name) → EndpointClient
        self._endpoint_paths: Dict[tuple, str] = {}  # (kind, name) → dyn path
        self._task: Optional[asyncio.Task] = None
        self._closed = False
        # control-plane blackout tolerance (docs/resilience.md): entries
        # the store stopped vouching for are HELD (stale, purge-deadline)
        # instead of removed — a statestore that restarted empty must not
        # strip every model off the frontend while the workers are alive
        # and mid-rejoin. The disk cache (when enabled) lets a frontend
        # restarted mid-outage cold-start its model list.
        self._cp = control_plane.ControlPlanePolicy.from_env()
        self._cache = control_plane.maybe_cache(self._cp)
        self._cache_dirty = False
        self._raw: Dict[str, bytes] = {}  # key → last raw entry bytes
        self._stale_keys: Dict[str, float] = {}  # key → purge deadline
        self._cp_id = f"models-{uuid.uuid4().hex[:8]}"
        self._purge_task: Optional[asyncio.Task] = None

    @property
    def prefix(self) -> str:
        return f"{self.namespace}/models/"

    def start(self) -> None:
        self._task = asyncio.create_task(self._run())
        self._purge_task = asyncio.create_task(self._purge_loop())

    async def close(self) -> None:
        self._closed = True
        control_plane.state().forget_consumer(self._cp_id)
        for t in (self._task, self._purge_task):
            if t is None:
                continue
            t.cancel()
            try:
                await t
            except asyncio.CancelledError:
                pass
        for key in list(self._entry_model):
            await self._remove(key)

    async def _run(self) -> None:
        backoff = 0.5
        seeded = False
        while not self._closed:
            try:
                watcher = await self.drt.store.watch_prefix(
                    self.prefix, include_existing=True
                )
                backoff = 0.5
                async for ev in watcher:
                    if ev.type == "put":
                        self._mark_fresh(ev.key)
                        await self._add(ev.key, ev.value)
                    elif ev.type == "delete" and ev.resync and (
                        self._cp.stale_serve and ev.key in self._entry_model
                    ):
                        # the (possibly restarted-empty) store no longer
                        # vouches for this entry, but nothing positively
                        # observed its deletion: hold the model as stale
                        self._mark_stale(ev.key)
                    elif ev.type == "delete":
                        await self._remove(ev.key)
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("model watch error; reconnecting")
            if self._closed:
                return
            if not seeded and not self._entry_model:
                # cold start against a DEAD statestore: serve from the disk
                # cache (entries marked stale) while the reconnect loop
                # below keeps dialing; without a cache this keeps retrying —
                # the runtime's create() already failed fast for the
                # no-cache, never-connected case
                seeded = True
                await self._seed_from_cache()
            # watch ended: statestore connection lost. Models stay registered
            # (workers may still be fine) until the fresh snapshot replaces
            # the state; entries absent from it are then held as stale
            # (purged after the grace window) — or removed immediately with
            # stale-serve off (the pre-blackout behavior).
            try:
                try:
                    await self.drt.store.get("__ping__")
                except (ConnectionError, RuntimeError):
                    await self.drt.reconnect_store()
                snapshot = await self.drt.store.get_prefix(self.prefix)
                for key in list(self._entry_model):
                    if key not in snapshot:
                        if self._cp.stale_serve:
                            self._mark_stale(key)
                        else:
                            await self._remove(key)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                logger.warning(
                    "model registry resync failed (%s); retrying in %.1fs",
                    e, backoff,
                )
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 10.0)

    # -- stale hold + disk cache (control_plane) ---------------------------

    def _mark_stale(self, key: str) -> None:
        if key not in self._stale_keys:
            self._stale_keys[key] = (
                asyncio.get_running_loop().time() + self._cp.stale_grace
            )
            control_plane.state().note_stale_entries(
                self._cp_id, len(self._stale_keys)
            )
            logger.warning(
                "model entry %s no longer vouched for by the store — "
                "holding it stale for %.0fs", key, self._cp.stale_grace,
            )

    def _mark_fresh(self, key: str) -> None:
        if self._stale_keys.pop(key, None) is not None:
            control_plane.state().note_stale_entries(
                self._cp_id, len(self._stale_keys)
            )

    async def _purge_loop(self) -> None:
        """Drop stale-held entries whose grace expired — but only while the
        store is CONNECTED: with the store down there is no fresh authority
        to justify removing anything (unlike instances, model entries have
        no probe plane of their own; their EndpointClients do)."""
        interval = max(min(self._cp.stale_grace / 4.0, 1.0), 0.05)
        while not self._closed:
            await asyncio.sleep(interval)
            await self._flush_cache()
            if not self._stale_keys:
                continue
            if not getattr(self.drt.store, "connected", True):
                continue
            now = asyncio.get_running_loop().time()
            for key, deadline in list(self._stale_keys.items()):
                if deadline <= now:
                    self._mark_fresh(key)
                    await self._remove(key)

    async def _seed_from_cache(self) -> bool:
        if self._cache is None:
            return False
        try:
            entries = await asyncio.to_thread(self._cache.load, self.prefix)
        except asyncio.CancelledError:
            raise
        except Exception:
            return False
        if not entries:
            return False
        control_plane.state().note_cache_serve()
        for key in sorted(entries):
            await self._add(key, entries[key])
            if key in self._entry_model:
                # only entries _add actually registered are held stale —
                # a cached token-wire entry it declined must not inflate
                # the stale gauge (it would degrade /health until purge)
                self._mark_stale(key)
        logger.warning(
            "cold-started model registry from the discovery cache: "
            "%d entr%s, marked stale until the store confirms them",
            len(entries), "y" if len(entries) == 1 else "ies",
        )
        return bool(self._entry_model)

    async def _flush_cache(self) -> None:
        """Persist the confirmed (non-stale) entry set for cold starts."""
        if self._cache is None or not self._cache_dirty or self._stale_keys:
            return
        self._cache_dirty = False
        entries = dict(self._raw)
        try:
            await asyncio.to_thread(self._cache.save, self.prefix, entries)
        except asyncio.CancelledError:
            raise
        except Exception:
            self._cache_dirty = True
            logger.debug("model cache write failed", exc_info=True)

    def _parse_key(self, key: str) -> Optional[tuple]:
        # {ns}/models/{kind}/{name}[@{instance}] — the instance suffix makes
        # entries per-worker; llmctl writes suffix-less entries. '@' (not ':')
        # so ollama-style model names like "llama3:8b" survive intact.
        tail = key[len(self.prefix):]
        if "/" not in tail:
            return None
        kind, name = tail.split("/", 1)
        name = name.rsplit("@", 1)[0] if "@" in name else name
        return kind, name

    async def _add(self, key: str, value: bytes) -> None:
        parsed = self._parse_key(key)
        if parsed is None:
            return
        kind, name = parsed
        try:
            entry = json.loads(value)
            endpoint_path = entry["endpoint"]
        except (ValueError, KeyError):
            logger.warning("malformed model entry at %s", key)
            return
        # remember the raw entry for the disk discovery cache (cold starts
        # replay exactly what the store last said)
        self._raw[key] = value
        self._cache_dirty = True
        if entry.get("wire", "openai") != "openai":
            # token-wire worker (cli/run --wire token): it speaks
            # PreprocessedRequest dicts, and this frontend has no tokenizer
            # to lower OpenAI requests — feeding it raw dicts would error
            # every request. Serve those fleets with
            # `in=http out=dyn://... --wire token --model-path ...`.
            logger.warning(
                "model %r at %s uses wire=%s; out=discover only routes "
                "openai-wire workers — skipping this entry",
                name, key, entry.get("wire"),
            )
            return
        if key in self._entry_model:
            return  # entry refresh for a model we already serve

        if parsed in self._clients:
            # another worker's entry for an already-served model: refcount it.
            # Traffic flows through the FIRST entry's endpoint path — if this
            # entry points somewhere else, its worker will never see requests
            # for this model name; surface that instead of silently dropping
            # it (ADVICE r2: endpoint-path divergence was invisible).
            known = self._endpoint_paths.get(parsed)
            if known is not None and endpoint_path != known:
                logger.warning(
                    "model %s/%s registered at %r by %s, but traffic is "
                    "routed to %r (first registration wins; align the "
                    "endpoint paths or use a distinct model name)",
                    kind, name, endpoint_path, key, known,
                )
            self._entry_model[key] = parsed
            self._model_keys[parsed].add(key)
            return

        from dynamo_tpu.runtime.distributed import parse_endpoint_path

        # a single bad entry must not crash the watch loop (the reconnect
        # path re-delivers existing keys, so a raise here would tear down
        # and re-dial every healthy model's client forever)
        try:
            ns, comp, ep = parse_endpoint_path(endpoint_path)
            client = await (
                self.drt.namespace(ns).component(comp).endpoint(ep).client(
                    self.router_mode, kv_block_size=self.kv_block_size,
                    policy=self.policy,
                )
            )
        except (ValueError, KeyError):
            logger.warning("unusable model entry at %s: %r", key, endpoint_path)
            return
        if kind == "chat":
            self.manager.add_chat_model(name, client)
        elif kind == "completions":
            self.manager.add_completions_model(name, client)
        else:
            logger.warning("unknown model kind %r at %s", kind, key)
            await client.close()
            return
        self._clients[parsed] = client
        self._endpoint_paths[parsed] = endpoint_path
        self._entry_model[key] = parsed
        self._model_keys[parsed] = {key}
        logger.info("model %r (%s) added via %s", name, kind, endpoint_path)

    async def _remove(self, key: str) -> None:
        self._raw.pop(key, None)
        self._cache_dirty = True
        parsed = self._entry_model.pop(key, None)
        if parsed is None:
            return
        keys = self._model_keys.get(parsed)
        if keys is not None:
            keys.discard(key)
            if keys:
                return  # other workers still serve this model
            del self._model_keys[parsed]
        client = self._clients.pop(parsed, None)
        self._endpoint_paths.pop(parsed, None)
        if client is not None:
            try:
                await client.close()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.debug("closing client for %s failed", key, exc_info=True)
        kind, name = parsed
        if kind == "chat":
            self.manager.remove_chat_model(name)
        elif kind == "completions":
            self.manager.remove_completions_model(name)
        logger.info("model %r (%s) removed", name, kind)
