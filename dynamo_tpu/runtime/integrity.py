"""End-to-end KV/output integrity: silent-corruption defense.

Hyperscaler fleets document silent data corruption (SDC) from defective
cores as a routine production event ("Cores that don't count", Hochschild
et al. HotOS'21; Meta's "Silent Data Corruptions at Scale"). This system
*amplifies* one bad host: KV pages are a cluster resource — host-tier
rehits, disagg transfers, prefix reads, and drain-time live migration all
replay pages long after the wire CRC (``runtime/codec.py``, transport-scope
only) stopped vouching for them. One SDC-afflicted worker can poison every
stream that ever touches its cache.

This module is the shared vocabulary of the integrity plane
(docs/resilience.md §Silent corruption):

- **Block content checksums**: a per-KV-block crc32 computed when the block
  is sealed (``allocator.note_tokens_computed``) that travels *with* the
  block through every tier — host-pool offload/rehit, disagg
  ``kv_blocks``/``read_blocks``/``migrate`` frames (header extension;
  checksum-less frames from old peers still parse), and migration staging —
  and is verified on every injection/adoption. A mismatch is a typed
  :class:`KvIntegrityError`: the block is dropped as a prefix miss and
  recomputed — never served, never a torn pool.
- **Trip accounting + quarantine**: every verification failure (and every
  output-watchdog trip) is a *trip* against this worker. ``trip_threshold``
  trips within ``trip_window`` seconds flip the process into **quarantine**:
  the health plane reports ``quarantined``, routers exclude the worker, the
  drain that follows must NOT migrate its (untrusted) pages — the migration
  coordinator degrades to resume directives — and only an operator
  (``llmctl worker unquarantine``) re-admits it.

``DYN_TPU_KV_INTEGRITY=0`` is THE zero-overhead gate: no checksum is ever
computed, no tracker or policy object is ever constructed, and the engine's
jitted step functions compile exactly the pre-integrity programs (tests
monkeypatch the constructors to prove it).

Threat model honesty: checksums are computed *at seal* by the worker that
computed the KV. They catch corruption that happens **after** the seal —
in HBM between seal and reuse, in host RAM in the spill tier, and on every
wire hop. A core that computes wrong values *before* the seal produces a
self-consistent checksum; that failure mode is what the output watchdog
(non-finite / exploding logits) and downstream byte-equality cover.
"""

from __future__ import annotations

import logging
import sys
import threading
import time
import zlib
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

logger = logging.getLogger(__name__)

ENV_KV_INTEGRITY = "DYN_TPU_KV_INTEGRITY"
ENV_TRIPS = "DYN_TPU_INTEGRITY_TRIPS"
ENV_WINDOW = "DYN_TPU_INTEGRITY_WINDOW"
ENV_LOGIT_LIMIT = "DYN_TPU_INTEGRITY_LOGIT_LIMIT"

# sentinel the jitted step functions substitute for a sampled token when the
# output watchdog flags a lane (non-finite or exploding logits): real token
# ids are always >= 0, so the host loop can detect a tripped lane from the
# fetched tokens alone — no extra device output, no extra transfer
WATCHDOG_TOKEN = -2


class KvIntegrityError(ValueError):
    """KV page bytes failed their content checksum: the page was corrupted
    after it was sealed (bad HBM/host RAM on the owner, or a bad wire hop).
    Raised *instead of* serving or injecting the bytes — the caller drops
    the block as a prefix miss and recomputes. The transfer plane maps it
    to a typed nack so the *sender* learns its pages are rotten and counts
    the trip against itself (the quarantine signal)."""


# PR3 clamping helpers live in the one shared home (runtime/envknobs.py);
# the local names are kept for the modules that historically imported the
# clamping contract from here (the tracing-imports-admission precedent)
from dynamo_tpu.runtime.envknobs import (  # noqa: E402
    env_clamped_float as _env_clamped_float,
    env_clamped_int as _env_clamped_int,
    env_flag as _env_flag,
)


@dataclass(frozen=True)
class IntegrityPolicy:
    """Knob bundle for the integrity plane (PR3 clamping contract:
    malformed / non-positive values fall back to defaults, in-range values
    clamp into the documented bounds).

    ``enabled``         DYN_TPU_KV_INTEGRITY (0 = zero-overhead gate: no
                        checksum ever computed, no watchdog variant built,
                        no tracker constructed).
    ``trip_threshold``  integrity trips within the window that flip this
                        worker into quarantine (clamped to [1, 1000]).
    ``trip_window``     seconds the trip window spans (clamped to
                        [1, 3600]).
    ``logit_limit``     |logit| above this marks a lane's output as
                        exploding even when finite (clamped to [10, 1e9]).
    """

    enabled: bool = True
    trip_threshold: int = 3
    trip_window: float = 60.0
    logit_limit: float = 1e4

    @classmethod
    def from_env(cls) -> "IntegrityPolicy":
        d = cls()
        return cls(
            enabled=_env_flag(ENV_KV_INTEGRITY, d.enabled),
            trip_threshold=_env_clamped_int(
                ENV_TRIPS, d.trip_threshold, 1, 1000
            ),
            trip_window=_env_clamped_float(
                ENV_WINDOW, d.trip_window, 1.0, 3600.0
            ),
            logit_limit=_env_clamped_float(
                ENV_LOGIT_LIMIT, d.logit_limit, 10.0, 1e9
            ),
        )


def maybe_from_env() -> Optional[IntegrityPolicy]:
    """The gate every integration point None-checks: ``None`` unless the
    integrity plane is enabled — with ``DYN_TPU_KV_INTEGRITY=0`` no policy
    object is ever constructed (the PR9/PR12 zero-overhead pattern)."""
    if not _env_flag(ENV_KV_INTEGRITY, True):
        return None
    return IntegrityPolicy.from_env()


def enabled() -> bool:
    """Cheap boolean form of the gate (one env read, no object)."""
    return _env_flag(ENV_KV_INTEGRITY, True)


# ---------------------------------------------------------------------------
# block content checksums
# ---------------------------------------------------------------------------


def _arr_crc(crc: int, arr: Any) -> int:
    # tobytes() on an ascontiguousarray: works for every dtype in the KV
    # tiers (bf16 via ml_dtypes has no stable buffer protocol everywhere)
    return zlib.crc32(np.ascontiguousarray(arr).tobytes(), crc)


def entry_checksum(k, v, k_scale=None, v_scale=None) -> int:
    """crc32 over ONE block's page bytes ([L, bs, KVH, D] ×2, plus the
    [L, bs] scale tables for int8 pools) — chained k | v | k_scale |
    v_scale, matching :func:`page_checksums` per-block order."""
    crc = _arr_crc(0, k)
    crc = _arr_crc(crc, v)
    if k_scale is not None:
        crc = _arr_crc(crc, k_scale)
        crc = _arr_crc(crc, v_scale)
    return crc


def page_checksums(k, v, k_scale=None, v_scale=None) -> List[int]:
    """Per-block crc32 over a stacked page set ([L, n, bs, KVH, D] ×2 and,
    for int8 pools, [L, n, bs] scale tables ×2): the wire/header form every
    transfer tier ships next to the pages."""
    n = k.shape[1]
    out: List[int] = []
    for i in range(n):
        out.append(entry_checksum(
            k[:, i], v[:, i],
            k_scale[:, i] if k_scale is not None else None,
            v_scale[:, i] if v_scale is not None else None,
        ))
    return out


def verify_pages(k, v, scales, crcs: Optional[Sequence[Optional[int]]],
                 where: str = "") -> None:
    """Verify a received page set against its travelling checksums.

    ``crcs`` entries of ``None``/negative mean "sender had no checksum for
    this block" (partial block, pre-integrity peer) and are skipped — a
    checksum-less frame always parses. Raises :class:`KvIntegrityError` at
    the first mismatching block, BEFORE any byte can land in a pool."""
    if crcs is None:
        return
    ks, vs = (scales if scales is not None else (None, None))
    n = min(len(crcs), k.shape[1])
    for i in range(n):
        want = crcs[i]
        if want is None or (isinstance(want, int) and want < 0):
            continue
        got = entry_checksum(
            k[:, i], v[:, i],
            ks[:, i] if ks is not None else None,
            vs[:, i] if vs is not None else None,
        )
        if got != int(want):
            raise KvIntegrityError(
                f"KV block {i} failed its content checksum"
                f"{' (' + where + ')' if where else ''}: "
                f"expected {int(want):#010x}, bytes hash to {got:#010x}"
            )


# ---------------------------------------------------------------------------
# trip accounting + quarantine (process-global, thread-safe)
# ---------------------------------------------------------------------------


class IntegrityTracker:
    """Process-global integrity outcome accounting + the quarantine latch.

    Constructed lazily on the FIRST trip/quarantine operation — with the
    integrity plane disabled nothing ever constructs it (the zero-overhead
    guard monkeypatches this constructor to prove it). Quarantine is a
    *source set* like drain sources: ``trips`` (self-detected corruption
    crossed the threshold) and ``store`` (``llmctl worker quarantine``)
    latch independently; an explicit operator unquarantine clears both and
    resets the trip window (the operator is vouching for the host)."""

    def __init__(self, policy: Optional[IntegrityPolicy] = None,
                 clock=time.monotonic):
        self._lock = threading.Lock()
        self._clock = clock
        self._policy = policy
        # kind → cumulative count; "kv" = checksum mismatches attributable
        # to THIS process's pages, "watchdog" = output-watchdog lane trips,
        # "remote" = corrupt pages OBSERVED from a peer (not self-blame)
        self.kv_failures_total = 0
        self.watchdog_trips_total = 0
        self.remote_failures_total = 0
        self._trips: deque = deque(maxlen=1024)  # (monotonic t, kind, where)
        self._quarantine_sources: set = set()
        self.quarantine_reason = ""
        self.quarantines_total = 0

    def _pol(self) -> IntegrityPolicy:
        # env read per trip, not per token: trips are failure events
        return self._policy or IntegrityPolicy.from_env()

    # -- trips -------------------------------------------------------------

    def note_trip(self, kind: str, where: str = "") -> bool:
        """Record one self-attributable integrity trip ("kv" | "watchdog").
        Returns True when this trip crossed the threshold and latched
        quarantine."""
        pol = self._pol()
        now = self._clock()
        with self._lock:
            if kind == "watchdog":
                self.watchdog_trips_total += 1
            else:
                self.kv_failures_total += 1
            self._trips.append((now, kind, where))
            in_window = sum(
                1 for t, _, _ in self._trips
                if now - t <= pol.trip_window
            )
            if (
                in_window >= pol.trip_threshold
                and "trips" not in self._quarantine_sources
            ):
                self._quarantine_sources.add("trips")
                self.quarantine_reason = (
                    f"{in_window} integrity trips within "
                    f"{pol.trip_window:.0f}s (last: {kind}"
                    f"{' @' + where if where else ''})"
                )
                self.quarantines_total += 1
                logger.error(
                    "worker QUARANTINED: %s — serving stops, pages are "
                    "untrusted (drain will resume, not migrate); "
                    "`llmctl worker unquarantine` re-admits after repair",
                    self.quarantine_reason,
                )
                return True
        logger.error(
            "integrity trip (%s%s): %d/%d within the window", kind,
            " @" + where if where else "", in_window, pol.trip_threshold,
        )
        return False

    def note_remote_failure(self, where: str = "") -> None:
        """A peer's pages failed verification HERE: observability only —
        the blame (and the quarantine trip) belongs to the sender, which
        learns via the typed nack."""
        with self._lock:
            self.remote_failures_total += 1
        logger.warning("rejected corrupt KV pages from a peer (%s)", where)

    # -- quarantine latch --------------------------------------------------

    @property
    def quarantined(self) -> bool:
        with self._lock:
            return bool(self._quarantine_sources)

    def quarantine(self, source: str = "store", reason: str = "") -> None:
        with self._lock:
            fresh = not self._quarantine_sources
            self._quarantine_sources.add(source)
            if reason or fresh:
                self.quarantine_reason = reason or f"ordered via {source}"
            if fresh:
                self.quarantines_total += 1
        # chaos-plane observation hook (docs/chaos.md): one dict-get unless
        # runtime/chaos.py is imported and armed; outside _lock (the
        # observer locks itself)
        ch = sys.modules.get("dynamo_tpu.runtime.chaos")
        if ch is not None:
            ch.note_event("quarantine", latched=True, source=source,
                          reason=reason)

    def clear_quarantine(self, source: Optional[str] = None) -> None:
        """``source=None`` is the operator unquarantine: every source is
        cleared AND the trip window is reset (without the reset the very
        next health check would re-latch off the old trips)."""
        with self._lock:
            if source is None:
                self._quarantine_sources.clear()
                self._trips.clear()
                self.quarantine_reason = ""
            else:
                self._quarantine_sources.discard(source)
                if not self._quarantine_sources:
                    self.quarantine_reason = ""
            still = bool(self._quarantine_sources)
        ch = sys.modules.get("dynamo_tpu.runtime.chaos")
        if ch is not None:
            ch.note_event("quarantine", latched=still, source=source or "*")

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return {
                "kv_integrity_failures_total": self.kv_failures_total,
                "watchdog_trips_total": self.watchdog_trips_total,
                "kv_integrity_remote_failures_total":
                    self.remote_failures_total,
                "quarantined": int(bool(self._quarantine_sources)),
            }


_TRACKER: Optional[IntegrityTracker] = None
_TRACKER_LOCK = threading.Lock()


def tracker() -> IntegrityTracker:
    """The process-global tracker, constructed on first use (never with the
    plane disabled — callers sit behind the :func:`maybe_from_env` gate)."""
    global _TRACKER
    if _TRACKER is None:
        with _TRACKER_LOCK:
            if _TRACKER is None:
                _TRACKER = IntegrityTracker()
    return _TRACKER


def note_trip(kind: str, where: str = "") -> bool:
    return tracker().note_trip(kind, where)


def note_remote_failure(where: str = "") -> None:
    tracker().note_remote_failure(where)


def clear_quarantine(source: Optional[str] = None) -> None:
    """Constructor-free clear: a no-op until something actually latched
    (the store control loop syncs an absent key without building state)."""
    t = _TRACKER
    if t is not None:
        t.clear_quarantine(source)


def quarantined() -> bool:
    """Constructor-free read: False until something actually built the
    tracker (the health monitor polls this every check tick)."""
    t = _TRACKER
    return t is not None and t.quarantined


def quarantine_reason() -> str:
    t = _TRACKER
    return t.quarantine_reason if t is not None else ""


def counters() -> Dict[str, int]:
    """Constructor-free counters for the metrics publisher: zeros until a
    trip/quarantine ever happened in this process."""
    t = _TRACKER
    if t is None:
        return {
            "kv_integrity_failures_total": 0,
            "watchdog_trips_total": 0,
            "kv_integrity_remote_failures_total": 0,
            "quarantined": 0,
        }
    return t.counters()


def reset_for_tests() -> None:
    """Drop the process-global tracker (conftest autouse reset: one test's
    trips/quarantine must not bleed into another's health assertions)."""
    global _TRACKER
    with _TRACKER_LOCK:
        _TRACKER = None
