"""Two-part framed wire codec: length-prefixed header + body with checksum.

Frame layout (capability parity with the reference's TwoPartCodec,
lib/runtime/src/pipeline/network/codec/two_part.rs — re-specified, not ported):

    [8B LE header_len][8B LE body_len][8B LE checksum][header][body]

checksum = crc32(header || body), zero-extended to 8 bytes. (The reference
uses xxh3; crc32 is chosen here because it is equally cheap from Python
(zlib) and C++ (zlib/hardware), keeping the native codec trivially
wire-compatible. Content-addressed KV hashing still uses xxh3 — different
concern, different hash.)

Max-size enforcement guards both sides against corrupt/hostile frames.

Native-path note (r5 determination): the per-frame cost here is crc32
(zlib, C) + one struct.pack + bytes concat — already C-dominated, so
swapping in native/codec_core.so for in-process framing has no measurable
headroom. codec_core.so exists for NON-Python engines/components speaking
this wire format (its layout is differential-tested against this file);
the measured Python frontend hot spot was SSE chunk serialization, fixed
by the template fast path in llm/http/service.py.
"""

from __future__ import annotations

import asyncio
import struct
import zlib
from dataclasses import dataclass
from typing import Optional, Tuple

PRELUDE = struct.Struct("<QQQ")
MAX_HEADER = 16 * 1024 * 1024
MAX_BODY = 1024 * 1024 * 1024


class CodecError(Exception):
    pass


@dataclass(frozen=True)
class TwoPartMessage:
    header: bytes
    body: bytes


def checksum(header: bytes, body: bytes) -> int:
    c = zlib.crc32(header)
    return zlib.crc32(body, c)


def encode(msg: TwoPartMessage) -> bytes:
    if len(msg.header) > MAX_HEADER:
        raise CodecError(f"header too large: {len(msg.header)}")
    if len(msg.body) > MAX_BODY:
        raise CodecError(f"body too large: {len(msg.body)}")
    return (
        PRELUDE.pack(len(msg.header), len(msg.body), checksum(msg.header, msg.body))
        + msg.header
        + msg.body
    )


def decode(buf: bytes) -> Tuple[Optional[TwoPartMessage], bytes]:
    """Try to decode one frame; returns (message | None, remaining bytes)."""
    if len(buf) < PRELUDE.size:
        return None, buf
    hlen, blen, csum = PRELUDE.unpack_from(buf)
    _validate_sizes(hlen, blen)
    total = PRELUDE.size + hlen + blen
    if len(buf) < total:
        return None, buf
    header = buf[PRELUDE.size : PRELUDE.size + hlen]
    body = buf[PRELUDE.size + hlen : total]
    if checksum(header, body) != csum:
        raise CodecError("checksum mismatch")
    return TwoPartMessage(bytes(header), bytes(body)), buf[total:]


def _validate_sizes(hlen: int, blen: int) -> None:
    if hlen > MAX_HEADER:
        raise CodecError(f"header length {hlen} exceeds max {MAX_HEADER}")
    if blen > MAX_BODY:
        raise CodecError(f"body length {blen} exceeds max {MAX_BODY}")


# -- asyncio stream helpers --------------------------------------------------

async def read_frame(reader: asyncio.StreamReader) -> TwoPartMessage:
    """Read one frame; raises IncompleteReadError on clean EOF."""
    prelude = await reader.readexactly(PRELUDE.size)
    hlen, blen, csum = PRELUDE.unpack(prelude)
    _validate_sizes(hlen, blen)
    header = await reader.readexactly(hlen) if hlen else b""
    body = await reader.readexactly(blen) if blen else b""
    if checksum(header, body) != csum:
        raise CodecError("checksum mismatch")
    return TwoPartMessage(header, body)


async def write_frame(writer: asyncio.StreamWriter, msg: TwoPartMessage) -> None:
    writer.write(encode(msg))
    await writer.drain()
