"""Self-hosted event/queue plane: pub/sub subjects + durable work queues.

Capability parity with the reference's NATS usage (SURVEY.md §1):
- **pub/sub subjects** carry KV cache events (`kv_events`), hit-rate events
  and other scoped notifications (traits/events.rs:31-96);
- **work queues** back the disaggregated prefill queue (JetStream work-queue
  stream, examples/llm/utils/nats_queue.py) — at-most-once pop with blocking
  waiters.

One asyncio TCP service speaking the framed codec; the request/response RPC
plane does NOT go through here (workers are dialed directly — see rpc.py —
which removes a broker hop the reference pays on every request).

Run standalone: ``python -m dynamo_tpu.runtime.bus --port 37902``.
"""

from __future__ import annotations

import argparse
import asyncio
import itertools
import json
import logging
import uuid
from collections import deque
from typing import AsyncIterator, Deque, Dict, List, Optional, Tuple

from dynamo_tpu.runtime.codec import TwoPartMessage, read_frame, write_frame

logger = logging.getLogger(__name__)

DEFAULT_PORT = 37902


class _Conn:
    """One client connection's outbound side: a bounded queue drained by a
    dedicated writer task. Every server→client frame goes through here, which
    (a) serializes writes (no frame interleaving between concurrent
    dispatches) and (b) decouples publishers from slow subscribers — a
    stalled subscriber fills its own outbox and starts dropping instead of
    blocking whoever published (round-1 weakness W6; same bounded-queue
    design as statestore.py watches)."""

    __slots__ = ("writer", "outbox", "task", "alive", "dropped")

    def __init__(self, writer: asyncio.StreamWriter, maxsize: int = 512):
        self.writer = writer
        # items are (msg, fut|None): fut resolves True once the frame has
        # been written to the socket, False if the connection died first
        self.outbox: asyncio.Queue = asyncio.Queue(maxsize=maxsize)
        self.alive = True
        self.dropped = 0
        self.task = asyncio.create_task(self._drain())

    async def _drain(self) -> None:
        fut = None
        try:
            while True:
                msg, fut = await self.outbox.get()
                await write_frame(self.writer, msg)
                if fut is not None and not fut.done():
                    fut.set_result(True)
                fut = None
        except (ConnectionError, RuntimeError, OSError, asyncio.CancelledError):
            self.alive = False
            if fut is not None and not fut.done():  # mid-write casualty
                fut.set_result(False)
            self._fail_queued()

    def _fail_queued(self) -> None:
        while not self.outbox.empty():
            _, fut = self.outbox.get_nowait()
            if fut is not None and not fut.done():
                fut.set_result(False)

    def send(self, msg: TwoPartMessage) -> bool:
        """Best-effort enqueue; False = connection dead or outbox full.
        For droppable pushes (pub/sub events) ONLY — replies and queue-item
        deliveries must use send_reliable, a dropped reply hangs the caller."""
        if not self.alive:
            return False
        try:
            self.outbox.put_nowait((msg, None))
            return True
        except asyncio.QueueFull:
            self.dropped += 1
            if self.dropped in (1, 100, 10000):
                logger.warning(
                    "bus connection outbox full (%d drops): slow consumer",
                    self.dropped,
                )
            return False

    async def send_reliable(self, msg: TwoPartMessage) -> bool:
        """Backpressured enqueue confirmed at SOCKET-WRITE time: resolves
        True only after the frame actually reached the kernel buffer, False
        if the connection died first — so a qpush/qpop delivery reported
        `delivered` can't be silently discarded by a dying drain task (the
        caller requeues or tries the next waiter instead)."""
        if not self.alive:
            return False
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        await self.outbox.put((msg, fut))
        if not self.alive:
            # drain died between the liveness check and our put: its cleanup
            # may have missed this item, so fail queued entries ourselves
            self._fail_queued()
        return await fut

    def close(self) -> None:
        self.alive = False
        self.task.cancel()
        self._fail_queued()


class MessageBusServer:
    def __init__(self, host: str = "127.0.0.1", port: int = DEFAULT_PORT):
        self.host = host
        self.port = port
        # subject → {sub_id → conn}
        self._subs: Dict[str, Dict[str, _Conn]] = {}
        self._queues: Dict[str, Deque[bytes]] = {}
        # queue → waiters (conn, req_id)
        self._queue_waiters: Dict[str, Deque[Tuple[_Conn, int]]] = {}
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> None:
        from dynamo_tpu.runtime.netutil import TrackedServer

        self._server = TrackedServer(self._handle, self.host, self.port)
        self.port = await self._server.start()
        logger.info("message bus listening on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._server:
            await self._server.stop()

    @property
    def url(self) -> str:
        return f"{self.host}:{self.port}"

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        conn = _Conn(writer)
        conn_subs: List[Tuple[str, str]] = []  # (subject, sub_id)
        try:
            while True:
                try:
                    frame = await read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    return
                req = json.loads(frame.header)
                reply = await self._dispatch(req, frame.body, conn, conn_subs)
                if reply is not None:
                    reply["id"] = req.get("id")
                    await conn.send_reliable(
                        TwoPartMessage(json.dumps(reply).encode(), b"")
                    )
        finally:
            for subject, sub_id in conn_subs:
                subs = self._subs.get(subject)
                if subs:
                    subs.pop(sub_id, None)
            for waiters in self._queue_waiters.values():
                remaining = deque((c, rid) for c, rid in waiters if c is not conn)
                waiters.clear()
                waiters.extend(remaining)
            conn.close()
            writer.close()

    async def _dispatch(self, req, body, conn: _Conn, conn_subs) -> Optional[dict]:
        op = req.get("op")
        if op == "pub":
            subject = req["subject"]
            dead = []
            for sub_id, c in list(self._subs.get(subject, {}).items()):
                delivered = c.send(
                    TwoPartMessage(
                        json.dumps(
                            {"push": "msg", "subject": subject, "sub_id": sub_id}
                        ).encode(),
                        body,
                    )
                )
                if not delivered and not c.alive:
                    dead.append(sub_id)
                # alive-but-full: event dropped for that subscriber only
            for sid in dead:
                self._subs[subject].pop(sid, None)
            return {"ok": True}
        if op == "sub":
            sub_id = req.get("sub_id") or uuid.uuid4().hex
            self._subs.setdefault(req["subject"], {})[sub_id] = conn
            conn_subs.append((req["subject"], sub_id))
            return {"ok": True, "sub_id": sub_id}
        if op == "unsub":
            subs = self._subs.get(req["subject"], {})
            subs.pop(req["sub_id"], None)
            return {"ok": True}
        if op == "qpush":
            queue = req["queue"]
            waiters = self._queue_waiters.get(queue)
            while waiters:  # try every live waiter before enqueueing
                c, req_id = waiters.popleft()
                delivered = await c.send_reliable(
                    TwoPartMessage(
                        json.dumps({"id": req_id, "ok": True, "found": True}).encode(),
                        body,
                    )
                )
                if delivered:
                    return {"ok": True}
                # waiter connection died: try the next one
            self._queues.setdefault(queue, deque()).append(body)
            return {"ok": True}
        if op == "qpop":
            queue = req["queue"]
            q = self._queues.get(queue)
            if q:
                return_body = q.popleft()
                sent = await conn.send_reliable(
                    TwoPartMessage(
                        json.dumps({"id": req.get("id"), "ok": True, "found": True}).encode(),
                        return_body,
                    )
                )
                if not sent:  # popper died: don't lose the item
                    q.appendleft(return_body)
                return None  # reply already sent (with body)
            if req.get("block"):
                self._queue_waiters.setdefault(queue, deque()).append(
                    (conn, req.get("id"))
                )
                return None  # reply deferred until a push arrives
            return {"ok": True, "found": False}
        if op == "qcancel":
            # remove this connection's blocked pop (client-side cancellation)
            waiters = self._queue_waiters.get(req["queue"])
            if waiters:
                remaining = deque(
                    (c, rid) for c, rid in waiters
                    if not (c is conn and rid == req.get("cancel_id"))
                )
                waiters.clear()
                waiters.extend(remaining)
            return {"ok": True}
        if op == "qlen":
            return {"ok": True, "len": len(self._queues.get(req["queue"], ()))}
        return {"ok": False, "error": f"unknown op {op!r}"}


class Subscription:
    """Async iterator over messages for one subject subscription."""

    def __init__(self, client: "MessageBusClient", subject: str, sub_id: str):
        self.client = client
        self.subject = subject
        self.sub_id = sub_id
        self.queue: asyncio.Queue = asyncio.Queue()

    def __aiter__(self) -> AsyncIterator[bytes]:
        return self._iter()

    async def _iter(self) -> AsyncIterator[bytes]:
        while True:
            item = await self.queue.get()
            if item is None:
                return
            yield item

    async def cancel(self) -> None:
        self.client._subs.pop(self.sub_id, None)
        try:
            await self.client._call({"op": "unsub", "subject": self.subject, "sub_id": self.sub_id})
        except ConnectionError:
            pass
        self.queue.put_nowait(None)


class MessageBusClient:
    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._ids = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        self._subs: Dict[str, Subscription] = {}
        self._reader_task: Optional[asyncio.Task] = None
        self._send_lock = asyncio.Lock()

    @classmethod
    async def connect(cls, url: str) -> "MessageBusClient":
        host, _, port = url.rpartition(":")
        c = cls(host or "127.0.0.1", int(port))
        c._reader, c._writer = await asyncio.open_connection(c.host, c.port)
        c._reader_task = asyncio.create_task(c._read_loop())
        return c

    async def close(self) -> None:
        if self._reader_task:
            self._reader_task.cancel()
        if self._writer:
            self._writer.close()
        for s in self._subs.values():
            s.queue.put_nowait(None)

    async def _read_loop(self) -> None:
        try:
            while True:
                frame = await read_frame(self._reader)
                h = json.loads(frame.header)
                if h.get("push") == "msg":
                    sub = self._subs.get(h["sub_id"])
                    if sub is not None:
                        sub.queue.put_nowait(frame.body)
                    continue
                fut = self._pending.pop(h.get("id"), None)
                if fut is not None and not fut.done():
                    fut.set_result((h, frame.body))
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError):
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionError("bus connection lost"))
            for s in self._subs.values():
                s.queue.put_nowait(None)

    async def _call(self, req: dict, body: bytes = b"") -> Tuple[dict, bytes]:
        req_id = next(self._ids)
        req["id"] = req_id
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        async with self._send_lock:
            await write_frame(self._writer, TwoPartMessage(json.dumps(req).encode(), body))
        reply, rbody = await fut
        if not reply.get("ok"):
            raise RuntimeError(f"bus error: {reply.get('error')}")
        return reply, rbody

    # -- public API ----------------------------------------------------------

    async def publish(self, subject: str, payload: bytes) -> None:
        await self._call({"op": "pub", "subject": subject}, payload)

    async def subscribe(self, subject: str) -> Subscription:
        sub_id = uuid.uuid4().hex
        sub = Subscription(self, subject, sub_id)
        self._subs[sub_id] = sub
        await self._call({"op": "sub", "subject": subject, "sub_id": sub_id})
        return sub

    async def queue_push(self, queue: str, payload: bytes) -> None:
        await self._call({"op": "qpush", "queue": queue}, payload)

    async def queue_pop(self, queue: str, block: bool = False) -> Optional[bytes]:
        """Pop one item; with block=True waits for a push. Cancellation-safe:
        a cancelled blocking pop withdraws its server-side waiter, and an item
        that raced the cancellation is re-pushed rather than lost."""
        req_id = next(self._ids)
        req = {"op": "qpop", "queue": queue, "block": block, "id": req_id}
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        async with self._send_lock:
            await write_frame(self._writer, TwoPartMessage(json.dumps(req).encode(), b""))
        try:
            reply, body = await fut
        except asyncio.CancelledError:
            # leave a tombstone so a racing delivery is still captured, then
            # withdraw the waiter. Server→client writes are FIFO, so once the
            # qcancel reply arrives, any delivery for req_id has already been
            # read — the tombstone tells us whether to requeue it.
            tomb: asyncio.Future = asyncio.get_running_loop().create_future()
            self._pending[req_id] = tomb

            async def _cleanup():
                try:
                    await self._call({"op": "qcancel", "queue": queue, "cancel_id": req_id})
                    self._pending.pop(req_id, None)
                    if tomb.done():
                        r, b = tomb.result()
                        if r.get("found"):
                            await self.queue_push(queue, b)
                except (ConnectionError, RuntimeError):
                    pass

            asyncio.ensure_future(_cleanup())
            raise
        if not reply.get("ok"):
            raise RuntimeError(f"bus error: {reply.get('error')}")
        return body if reply.get("found") else None

    async def queue_len(self, queue: str) -> int:
        reply, _ = await self._call({"op": "qlen", "queue": queue})
        return int(reply.get("len", 0))


def main() -> None:
    p = argparse.ArgumentParser(description="dynamo_tpu message bus server")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=DEFAULT_PORT)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    async def run():
        server = MessageBusServer(args.host, args.port)
        await server.start()
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":
    main()
