"""Self-hosted event/queue plane: pub/sub subjects + durable work queues.

Capability parity with the reference's NATS usage (SURVEY.md §1):
- **pub/sub subjects** carry KV cache events (`kv_events`), hit-rate events
  and other scoped notifications (traits/events.rs:31-96) — fire-and-forget,
  ephemeral, exactly like NATS core;
- **work queues** back the disaggregated prefill queue (JetStream work-queue
  stream, examples/llm/utils/nats_queue.py:155) with JetStream's durability
  semantics: queued items and unacked in-flight deliveries survive a server
  bounce via a WAL + snapshot (same structure as statestore.py), ack-mode
  pops (``queue_pop_acked``/``queue_ack``) are at-least-once — an item whose
  consumer or server dies before the ack is redelivered — and the plain
  ``queue_pop`` keeps its original at-most-once contract.

The client reconnects transparently: on connection loss it redials with
backoff, re-subscribes, and re-sends still-pending requests (qpush retries
make delivery at-least-once across a bounce — consumers must tolerate
duplicates, which the disagg prefill path does: a duplicate prefill lands as
a stale completion).

One asyncio TCP service speaking the framed codec; the request/response RPC
plane does NOT go through here (workers are dialed directly — see rpc.py —
which removes a broker hop the reference pays on every request).

Run standalone: ``python -m dynamo_tpu.runtime.bus --port 37902 --data-dir ...``.
"""

from __future__ import annotations

import argparse
import asyncio
import base64
import itertools
import json
import logging
import os
import uuid
from collections import deque
from typing import AsyncIterator, Deque, Dict, List, Optional, Tuple

from dynamo_tpu.runtime import control_plane, faults
from dynamo_tpu.runtime.codec import TwoPartMessage, read_frame, write_frame

logger = logging.getLogger(__name__)

DEFAULT_PORT = 37902


class _Conn:
    """One client connection's outbound side: a bounded queue drained by a
    dedicated writer task. Every server→client frame goes through here, which
    (a) serializes writes (no frame interleaving between concurrent
    dispatches) and (b) decouples publishers from slow subscribers — a
    stalled subscriber fills its own outbox and starts dropping instead of
    blocking whoever published (round-1 weakness W6; same bounded-queue
    design as statestore.py watches)."""

    __slots__ = ("writer", "outbox", "task", "alive", "dropped")

    def __init__(self, writer: asyncio.StreamWriter, maxsize: int = 512):
        self.writer = writer
        # items are (msg, fut|None): fut resolves True once the frame has
        # been written to the socket, False if the connection died first
        self.outbox: asyncio.Queue = asyncio.Queue(maxsize=maxsize)
        self.alive = True
        self.dropped = 0
        self.task = asyncio.create_task(self._drain())

    async def _drain(self) -> None:
        fut = None
        try:
            while True:
                msg, fut = await self.outbox.get()
                await write_frame(self.writer, msg)
                if fut is not None and not fut.done():
                    fut.set_result(True)
                fut = None
        except (ConnectionError, RuntimeError, OSError, asyncio.CancelledError):
            self.alive = False
            if fut is not None and not fut.done():  # mid-write casualty
                fut.set_result(False)
            self._fail_queued()

    def _fail_queued(self) -> None:
        while not self.outbox.empty():
            _, fut = self.outbox.get_nowait()
            if fut is not None and not fut.done():
                fut.set_result(False)

    def send(self, msg: TwoPartMessage) -> bool:
        """Best-effort enqueue; False = connection dead or outbox full.
        For droppable pushes (pub/sub events) ONLY — replies and queue-item
        deliveries must use send_reliable, a dropped reply hangs the caller."""
        if not self.alive:
            return False
        try:
            self.outbox.put_nowait((msg, None))
            return True
        except asyncio.QueueFull:
            self.dropped += 1
            if self.dropped in (1, 100, 10000):
                logger.warning(
                    "bus connection outbox full (%d drops): slow consumer",
                    self.dropped,
                )
            return False

    async def send_reliable(self, msg: TwoPartMessage) -> bool:
        """Backpressured enqueue confirmed at SOCKET-WRITE time: resolves
        True only after the frame actually reached the kernel buffer, False
        if the connection died first — so a qpush/qpop delivery reported
        `delivered` can't be silently discarded by a dying drain task (the
        caller requeues or tries the next waiter instead)."""
        if not self.alive:
            return False
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        await self.outbox.put((msg, fut))
        if not self.alive:
            # drain died between the liveness check and our put: its cleanup
            # may have missed this item, so fail queued entries ourselves
            self._fail_queued()
        return await fut

    def close(self) -> None:
        self.alive = False
        self.task.cancel()
        self._fail_queued()


class MessageBusServer:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = DEFAULT_PORT,
        data_dir: Optional[str] = None,
        snapshot_every: int = 10_000,
    ):
        self.host = host
        self.port = port
        self.data_dir = data_dir
        self.snapshot_every = snapshot_every
        # subject → {sub_id → conn}
        self._subs: Dict[str, Dict[str, _Conn]] = {}
        # queue → deque of (msg_id, body)
        self._queues: Dict[str, Deque[Tuple[str, bytes]]] = {}
        # queue → waiters (conn, req_id, wants_ack)
        self._queue_waiters: Dict[str, Deque[Tuple[_Conn, int, bool]]] = {}
        # msg_id → (queue, body, conn): delivered in ack mode, not yet acked
        self._inflight: Dict[str, Tuple[str, bytes, _Conn]] = {}
        # recently seen push msg_ids (bounded): reconnect-replay dedup
        self._push_ids: Dict[str, None] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._wal = None
        self._wal_records = 0
        self._snapshot_task: Optional[asyncio.Task] = None
        if data_dir is not None:
            os.makedirs(data_dir, exist_ok=True)
            self._restore()
            self._wal = open(self._wal_path, "a")

    # -- durability (WAL + snapshot; same shape as statestore.py) ------------

    @property
    def _snap_path(self) -> str:
        return os.path.join(self.data_dir, "bus-snapshot.json")

    @property
    def _wal_path(self) -> str:
        return os.path.join(self.data_dir, "bus-wal.jsonl")

    @property
    def _wal_old_path(self) -> str:
        return os.path.join(self.data_dir, "bus-wal.old.jsonl")

    def _restore(self) -> None:
        """Load snapshot + replay WAL. In-flight (delivered, unacked) items
        are REDELIVERED: they go back to the FRONT of their queue — the
        consumer may have died with the server, and at-least-once means the
        work must not vanish with the ack."""
        inflight: Dict[str, Tuple[str, bytes]] = {}
        if os.path.exists(self._snap_path):
            try:
                with open(self._snap_path) as f:
                    snap = json.load(f)
            except (json.JSONDecodeError, OSError):
                logger.exception("corrupt bus snapshot; starting empty")
                snap = {"queues": {}, "inflight": []}
            for q, items in snap.get("queues", {}).items():
                self._queues[q] = deque(
                    (it["id"], base64.b64decode(it["v"])) for it in items
                )
            for it in snap.get("inflight", []):
                inflight[it["id"]] = (it["q"], base64.b64decode(it["v"]))
        n = 0
        for path in (self._wal_old_path, self._wal_path):
            if not os.path.exists(path):
                continue
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        logger.warning("truncated bus WAL tail dropped")
                        break
                    self._replay(rec, inflight)
                    n += 1
        self._wal_records = n
        # unacked in-flight at crash time → front of the queue
        for msg_id, (q, body) in inflight.items():
            self._queues.setdefault(q, deque()).appendleft((msg_id, body))
        # seed the push-id dedup window with every restored id: a client
        # replaying a pre-crash push must not double-enqueue
        for items in self._queues.values():
            for mid, _ in items:
                self._note_push_id(mid)
        total = sum(len(q) for q in self._queues.values())
        if total:
            logger.info(
                "bus restored %d queued items (%d were unacked in-flight, "
                "%d WAL records)", total, len(inflight), n,
            )

    def _note_push_id(self, msg_id: str, cap: int = 8192) -> None:
        self._push_ids[msg_id] = None
        while len(self._push_ids) > cap:
            self._push_ids.pop(next(iter(self._push_ids)))

    def _replay(self, rec: dict, inflight: Dict[str, Tuple[str, bytes]]) -> None:
        op = rec.get("op")
        if op == "push":
            self._note_push_id(rec["id"])
            self._queues.setdefault(rec["q"], deque()).append(
                (rec["id"], base64.b64decode(rec["v"]))
            )
        elif op == "deliver":
            q = self._queues.get(rec["q"])
            if q:
                for i, (mid, body) in enumerate(q):
                    if mid == rec["id"]:
                        del q[i]
                        inflight[mid] = (rec["q"], body)
                        break
        elif op == "ack":
            if rec["id"] not in inflight:
                # acked a non-inflight id: it was a plain (at-most-once) pop
                q = self._queues.get(rec["q"])
                if q:
                    for i, (mid, _) in enumerate(q):
                        if mid == rec["id"]:
                            del q[i]
                            break
            inflight.pop(rec["id"], None)
        elif op == "requeue":
            item = inflight.pop(rec["id"], None)
            if item is not None:
                self._queues.setdefault(item[0], deque()).appendleft(
                    (rec["id"], item[1])
                )

    def _log(self, rec: dict) -> None:
        if self._wal is None:
            return
        self._wal.write(json.dumps(rec) + "\n")
        self._wal.flush()
        self._wal_records += 1
        if (
            self._wal_records >= self.snapshot_every
            and (self._snapshot_task is None or self._snapshot_task.done())
        ):
            self._wal.close()
            if os.path.exists(self._wal_old_path):
                # rare (only after a failed snapshot): chunked append so a
                # large WAL never sits in memory; the file is closed and no
                # longer written, so the copy is race-free
                import shutil

                with open(self._wal_old_path, "ab") as dst, \
                        open(self._wal_path, "rb") as src:
                    shutil.copyfileobj(src, dst)
                os.remove(self._wal_path)
            else:
                os.replace(self._wal_path, self._wal_old_path)
            self._wal = open(self._wal_path, "w")
            self._wal_records = 0
            snap = self._state_copy()
            self._snapshot_task = asyncio.get_running_loop().create_task(
                self._write_snapshot_async(snap)
            )

    def _state_copy(self) -> dict:
        return {
            "queues": {q: list(items) for q, items in self._queues.items()},
            "inflight": [
                (mid, q, body) for mid, (q, body, _) in self._inflight.items()
            ],
        }

    async def _write_snapshot_async(self, snap: dict) -> None:
        try:
            await asyncio.to_thread(self._dump_snapshot, snap)
            if os.path.exists(self._wal_old_path):
                os.remove(self._wal_old_path)
        except Exception:
            logger.exception("bus snapshot failed; wal.old retained")

    def _dump_snapshot(self, snap: dict) -> None:
        out = {
            "queues": {
                q: [
                    {"id": mid, "v": base64.b64encode(body).decode()}
                    for mid, body in items
                ]
                for q, items in snap["queues"].items()
            },
            "inflight": [
                {"id": mid, "q": q, "v": base64.b64encode(body).decode()}
                for mid, q, body in snap["inflight"]
            ],
        }
        tmp = f"{self._snap_path}.tmp.{os.getpid()}.{uuid.uuid4().hex[:8]}"
        with open(tmp, "w") as f:
            json.dump(out, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._snap_path)

    async def start(self) -> None:
        from dynamo_tpu.runtime.netutil import TrackedServer

        self._server = TrackedServer(self._handle, self.host, self.port)
        self.port = await self._server.start()
        logger.info("message bus listening on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._server:
            await self._server.stop()
        if self._snapshot_task is not None and not self._snapshot_task.done():
            try:
                await self._snapshot_task
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("async snapshot failed during stop")
        if self._wal is not None:
            # graceful stop: compact so restart replays a snapshot, not a
            # log; the file IO runs off-loop so a slow disk can't stall
            # sibling servers sharing this event loop during shutdown
            state = self._state_copy()

            def _compact() -> None:
                self._dump_snapshot(state)
                self._wal.close()
                wal = open(self._wal_path, "w")
                wal.close()
                if os.path.exists(self._wal_old_path):
                    os.remove(self._wal_old_path)

            await asyncio.to_thread(_compact)
            self._wal = None

    @property
    def url(self) -> str:
        return f"{self.host}:{self.port}"

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        conn = _Conn(writer)
        conn_subs: List[Tuple[str, str]] = []  # (subject, sub_id)
        try:
            while True:
                try:
                    frame = await read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    return
                req = json.loads(frame.header)
                reply = await self._dispatch(req, frame.body, conn, conn_subs)
                if reply is not None:
                    reply["id"] = req.get("id")
                    await conn.send_reliable(
                        TwoPartMessage(json.dumps(reply).encode(), b"")
                    )
        finally:
            for subject, sub_id in conn_subs:
                subs = self._subs.get(subject)
                if subs:
                    subs.pop(sub_id, None)
            for waiters in self._queue_waiters.values():
                remaining = deque(
                    (c, rid, a) for c, rid, a in waiters if c is not conn
                )
                waiters.clear()
                waiters.extend(remaining)
            # ack-mode deliveries owned by this connection die with it:
            # redeliver — to a blocked waiter if one exists, else to the
            # front of the queue (they were next in line)
            owned = [
                mid for mid, (_, _, c) in self._inflight.items() if c is conn
            ]
            for mid in owned:
                q, body, _ = self._inflight.pop(mid)
                self._log({"op": "requeue", "id": mid})
                if not await self._deliver(q, mid, body):
                    self._queues.setdefault(q, deque()).appendleft((mid, body))
            conn.close()
            writer.close()

    async def _dispatch(self, req, body, conn: _Conn, conn_subs) -> Optional[dict]:
        op = req.get("op")
        if op == "pub":
            subject = req["subject"]
            dead = []
            for sub_id, c in list(self._subs.get(subject, {}).items()):
                delivered = c.send(
                    TwoPartMessage(
                        json.dumps(
                            {"push": "msg", "subject": subject, "sub_id": sub_id}
                        ).encode(),
                        body,
                    )
                )
                if not delivered and not c.alive:
                    dead.append(sub_id)
                # alive-but-full: event dropped for that subscriber only
            for sid in dead:
                self._subs[subject].pop(sid, None)
            return {"ok": True}
        if op == "sub":
            sub_id = req.get("sub_id") or uuid.uuid4().hex
            self._subs.setdefault(req["subject"], {})[sub_id] = conn
            conn_subs.append((req["subject"], sub_id))
            return {"ok": True, "sub_id": sub_id}
        if op == "unsub":
            subs = self._subs.get(req["subject"], {})
            subs.pop(req["sub_id"], None)
            return {"ok": True}
        if op == "qpush":
            queue = req["queue"]
            msg_id = req.get("msg_id") or uuid.uuid4().hex
            # idempotent under reconnect replay: a push the server applied
            # right before dying comes again with the same msg_id — applying
            # it twice would put two items under ONE id and corrupt the
            # id-keyed inflight tracking
            if msg_id in self._push_ids:
                return {"ok": True}
            self._note_push_id(msg_id)
            self._log({
                "op": "push", "q": queue, "id": msg_id,
                "v": base64.b64encode(body).decode(),
            })
            if not await self._deliver(queue, msg_id, body):
                self._queues.setdefault(queue, deque()).append((msg_id, body))
            return {"ok": True}
        if op == "qpop":
            queue = req["queue"]
            wants_ack = bool(req.get("ack"))
            q = self._queues.get(queue)
            if q:
                msg_id, return_body = q.popleft()
                if wants_ack:
                    # logged BEFORE the send: a crash after delivery but
                    # before the consumer's ack must redeliver (at-least-once)
                    self._log({"op": "deliver", "q": queue, "id": msg_id})
                    self._inflight[msg_id] = (queue, return_body, conn)
                sent = await conn.send_reliable(
                    TwoPartMessage(
                        json.dumps({
                            "id": req.get("id"), "ok": True, "found": True,
                            "msg_id": msg_id,
                        }).encode(),
                        return_body,
                    )
                )
                if not sent:  # popper died: don't lose the item
                    if wants_ack:
                        self._inflight.pop(msg_id, None)
                        self._log({"op": "requeue", "id": msg_id})
                    q.appendleft((msg_id, return_body))
                elif not wants_ack:
                    # at-most-once: consumed at delivery — logged only after
                    # the send succeeded, else a crash would drop the item
                    self._log({"op": "ack", "q": queue, "id": msg_id})
                return None  # reply already sent (with body)
            if req.get("block"):
                self._queue_waiters.setdefault(queue, deque()).append(
                    (conn, req.get("id"), wants_ack)
                )
                return None  # reply deferred until a push arrives
            return {"ok": True, "found": False}
        if op == "qack":
            item = self._inflight.pop(req["msg_id"], None)
            if item is not None:
                self._log({"op": "ack", "q": item[0], "id": req["msg_id"]})
            return {"ok": True, "known": item is not None}
        if op == "qcancel":
            # remove this connection's blocked pop (client-side cancellation)
            waiters = self._queue_waiters.get(req["queue"])
            if waiters:
                remaining = deque(
                    (c, rid, a) for c, rid, a in waiters
                    if not (c is conn and rid == req.get("cancel_id"))
                )
                waiters.clear()
                waiters.extend(remaining)
            return {"ok": True}
        if op == "qlen":
            return {"ok": True, "len": len(self._queues.get(req["queue"], ()))}
        return {"ok": False, "error": f"unknown op {op!r}"}

    async def _deliver(self, queue: str, msg_id: str, body: bytes) -> bool:
        """Offer an item to blocked waiters; True if one took delivery."""
        waiters = self._queue_waiters.get(queue)
        while waiters:
            c, req_id, wants_ack = waiters.popleft()
            if wants_ack:
                self._log({"op": "deliver", "q": queue, "id": msg_id})
                self._inflight[msg_id] = (queue, body, c)
            delivered = await c.send_reliable(
                TwoPartMessage(
                    json.dumps({
                        "id": req_id, "ok": True, "found": True,
                        "msg_id": msg_id,
                    }).encode(),
                    body,
                )
            )
            if delivered:
                if not wants_ack:
                    self._log({"op": "ack", "q": queue, "id": msg_id})
                return True
            # waiter connection died mid-delivery: roll back, try the next
            if wants_ack:
                self._inflight.pop(msg_id, None)
                self._log({"op": "requeue", "id": msg_id})
        return False


class Subscription:
    """Async iterator over messages for one subject subscription.

    The delivery queue is bounded (``MAX_QUEUE``): a consumer that stops
    iterating while the publisher keeps firing sheds the *oldest* buffered
    message instead of growing without bound (same drop-oldest policy as
    the KV-event publish bridge in runtime/distributed.py). Bus subjects
    carry event-plane traffic where the latest message supersedes older
    ones, so a slow consumer loses history, not liveness; ``dropped``
    counts the shed messages for observability."""

    MAX_QUEUE = 2048

    def __init__(self, client: "MessageBusClient", subject: str, sub_id: str):
        self.client = client
        self.subject = subject
        self.sub_id = sub_id
        self.queue: asyncio.Queue = asyncio.Queue(maxsize=self.MAX_QUEUE)
        self.dropped = 0

    def _offer(self, body: bytes) -> None:
        """Enqueue for the consumer, evicting oldest on overflow."""
        while self.queue.full():
            try:
                self.queue.get_nowait()
                self.dropped += 1
            except asyncio.QueueEmpty:  # pragma: no cover - racy full()
                break
        try:
            self.queue.put_nowait(body)
        except asyncio.QueueFull:  # pragma: no cover - single-threaded loop
            self.dropped += 1

    def _close(self) -> None:
        """Wake the consumer with the end-of-stream sentinel; on a full
        queue one data item is shed so the sentinel always fits."""
        while True:
            try:
                self.queue.put_nowait(None)
                return
            except asyncio.QueueFull:
                try:
                    self.queue.get_nowait()
                    self.dropped += 1
                except asyncio.QueueEmpty:  # pragma: no cover
                    pass

    def __aiter__(self) -> AsyncIterator[bytes]:
        return self._iter()

    async def _iter(self) -> AsyncIterator[bytes]:
        while True:
            item = await self.queue.get()
            if item is None:
                return
            yield item

    async def cancel(self) -> None:
        self.client._subs.pop(self.sub_id, None)
        try:
            await self.client._call({"op": "unsub", "subject": self.subject, "sub_id": self.sub_id})
        except ConnectionError:
            pass
        self._close()


class MessageBusClient:
    """Framed-codec bus client with transparent reconnection.

    On connection loss the read loop redials with backoff, re-subscribes
    every live subscription (same sub_id), and re-sends every still-pending
    request — a server bounce looks like latency, not an error. qpush
    retries carry a client msg_id, so delivery across a bounce is
    at-least-once (a push the old server processed right before dying can
    be duplicated; work-queue consumers are expected to tolerate that,
    matching JetStream semantics). Set ``reconnect=False`` for the old
    fail-fast behavior."""

    def __init__(self, host: str, port: int, reconnect: bool = True):
        self.host = host
        self.port = port
        self.reconnect = reconnect
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._ids = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        # req_id → (request dict, body): replayed verbatim on reconnect
        self._pending_reqs: Dict[int, Tuple[dict, bytes]] = {}
        self._subs: Dict[str, Subscription] = {}
        self._reader_task: Optional[asyncio.Task] = None
        self._send_lock = asyncio.Lock()
        self._closed = False
        # connectivity view for outage-aware publishers (control_plane
        # buffering): False while the read loop is redialing a dead server
        self._up = False
        # strong refs to fire-and-forget cleanup tasks (asyncio only weakly
        # references tasks; a GC'd cleanup would strand a queue item)
        self._bg_tasks: set = set()

    @property
    def connected(self) -> bool:
        return self._up and not self._closed

    @classmethod
    async def connect(cls, url: str, reconnect: bool = True) -> "MessageBusClient":
        host, _, port = url.rpartition(":")
        c = cls(host or "127.0.0.1", int(port), reconnect=reconnect)
        c._reader, c._writer = await faults.open_connection(c.host, c.port, plane="bus")
        c._up = True
        control_plane.note_bus(True)
        c._reader_task = asyncio.create_task(c._read_loop())
        return c

    async def close(self) -> None:
        self._closed = True
        if self._reader_task:
            self._reader_task.cancel()
        if self._writer:
            self._writer.close()
        for s in self._subs.values():
            s._close()

    def _fail_all(self) -> None:
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(ConnectionError("bus connection lost"))
        self._pending_reqs.clear()
        for s in self._subs.values():
            s._close()

    async def _reconnect(self) -> bool:
        delay = 0.05
        while not self._closed:
            try:
                self._reader, self._writer = await faults.open_connection(
                    self.host, self.port, plane="bus"
                )
            except OSError:
                await asyncio.sleep(delay)
                delay = min(delay * 2, 2.0)
                continue
            # restore server-side state: subscriptions first, then replay
            # every request still awaiting a reply (incl. blocked qpops)
            try:
                for sub in list(self._subs.values()):
                    await write_frame(self._writer, TwoPartMessage(
                        json.dumps({
                            "op": "sub", "subject": sub.subject,
                            "sub_id": sub.sub_id, "id": next(self._ids),
                        }).encode(), b"",
                    ))
                for req_id, (req, body) in list(self._pending_reqs.items()):
                    await write_frame(self._writer, TwoPartMessage(
                        json.dumps(req).encode(), body
                    ))
            except (ConnectionError, OSError):
                continue  # server bounced again mid-replay: redial
            logger.info("bus client reconnected to %s:%d", self.host, self.port)
            self._up = True
            control_plane.note_bus(True)
            return True
        return False

    async def _read_loop(self) -> None:
        while True:
            try:
                while True:
                    frame = await read_frame(self._reader)
                    h = json.loads(frame.header)
                    if h.get("push") == "msg":
                        sub = self._subs.get(h["sub_id"])
                        if sub is not None:
                            sub._offer(frame.body)
                        continue
                    rid = h.get("id")
                    fut = self._pending.pop(rid, None)
                    self._pending_reqs.pop(rid, None)
                    if fut is not None and not fut.done():
                        fut.set_result((h, frame.body))
            except asyncio.CancelledError:
                self._up = False
                self._fail_all()
                return
            except (asyncio.IncompleteReadError, ConnectionError, OSError):
                self._up = False
                if self._closed or not self.reconnect:
                    self._fail_all()
                    return
                control_plane.note_bus(False)
                try:
                    ok = await self._reconnect()
                except asyncio.CancelledError:
                    # close() landed while redialing: callers must not hang
                    self._fail_all()
                    return
                if not ok:
                    self._fail_all()
                    return

    async def _call(self, req: dict, body: bytes = b"") -> Tuple[dict, bytes]:
        req_id = next(self._ids)
        req["id"] = req_id
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        self._pending_reqs[req_id] = (req, body)
        try:
            async with self._send_lock:
                await write_frame(
                    self._writer, TwoPartMessage(json.dumps(req).encode(), body)
                )
        except (ConnectionError, OSError):
            if not self.reconnect or self._closed:
                self._pending.pop(req_id, None)
                self._pending_reqs.pop(req_id, None)
                raise
            # the read loop is redialing; the request replays on reconnect
        reply, rbody = await fut
        if not reply.get("ok"):
            raise RuntimeError(f"bus error: {reply.get('error')}")
        return reply, rbody

    # -- public API ----------------------------------------------------------

    async def publish(self, subject: str, payload: bytes) -> None:
        await self._call({"op": "pub", "subject": subject}, payload)

    async def subscribe(self, subject: str) -> Subscription:
        sub_id = uuid.uuid4().hex
        sub = Subscription(self, subject, sub_id)
        self._subs[sub_id] = sub
        await self._call({"op": "sub", "subject": subject, "sub_id": sub_id})
        return sub

    async def queue_push(self, queue: str, payload: bytes) -> None:
        await self._call(
            {"op": "qpush", "queue": queue, "msg_id": uuid.uuid4().hex}, payload
        )

    async def queue_pop(
        self, queue: str, block: bool = False, ack: bool = False,
        _want_msg_id: bool = False,
    ):
        """Pop one item; with block=True waits for a push. Cancellation-safe:
        a cancelled blocking pop withdraws its server-side waiter, and an item
        that raced the cancellation is re-pushed rather than lost. With
        ``ack=True`` the server keeps the item in-flight until
        :meth:`queue_ack` — at-least-once across consumer AND server death."""
        req_id = next(self._ids)
        req = {
            "op": "qpop", "queue": queue, "block": block, "id": req_id,
            "ack": ack,
        }
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        self._pending_reqs[req_id] = (req, b"")
        try:
            async with self._send_lock:
                await write_frame(
                    self._writer, TwoPartMessage(json.dumps(req).encode(), b"")
                )
        except (ConnectionError, OSError):
            if not self.reconnect or self._closed:
                self._pending.pop(req_id, None)
                self._pending_reqs.pop(req_id, None)
                raise
            # the read loop is redialing; the request replays on reconnect
        try:
            reply, body = await fut
        except asyncio.CancelledError:
            # leave a tombstone so a racing delivery is still captured, then
            # withdraw the waiter. Server→client writes are FIFO, so once the
            # qcancel reply arrives, any delivery for req_id has already been
            # read — the tombstone tells us whether to requeue it.
            tomb: asyncio.Future = asyncio.get_running_loop().create_future()
            self._pending[req_id] = tomb

            async def _cleanup():
                try:
                    await self._call({"op": "qcancel", "queue": queue, "cancel_id": req_id})
                    self._pending.pop(req_id, None)
                    self._pending_reqs.pop(req_id, None)
                    if tomb.done():
                        r, b = tomb.result()
                        if r.get("found"):
                            if ack and r.get("msg_id"):
                                # withdraw cleanly: the ack-mode item is
                                # in-flight under our name — requeue it
                                await self._call(
                                    {"op": "qack", "msg_id": r["msg_id"]}
                                )
                            await self.queue_push(queue, b)
                except (ConnectionError, RuntimeError):
                    pass

            t = asyncio.ensure_future(_cleanup())
            self._bg_tasks.add(t)
            t.add_done_callback(self._bg_tasks.discard)
            raise
        if not reply.get("ok"):
            raise RuntimeError(f"bus error: {reply.get('error')}")
        if not reply.get("found"):
            return (None, None) if _want_msg_id else None
        if _want_msg_id:
            return body, reply.get("msg_id")
        return body

    async def queue_pop_acked(
        self, queue: str, block: bool = False
    ) -> Optional[Tuple[bytes, str]]:
        """At-least-once pop: returns (body, msg_id); the item stays
        in-flight server-side until :meth:`queue_ack`(msg_id). Consumer or
        server death before the ack redelivers it (JetStream work-queue
        semantics, examples/llm/utils/nats_queue.py:155)."""
        res = await self.queue_pop(queue, block=block, ack=True, _want_msg_id=True)
        body, msg_id = res
        if body is None:
            return None
        return body, msg_id

    async def queue_ack(self, msg_id: str) -> None:
        await self._call({"op": "qack", "msg_id": msg_id})

    async def queue_len(self, queue: str) -> int:
        reply, _ = await self._call({"op": "qlen", "queue": queue})
        return int(reply.get("len", 0))


def main() -> None:
    p = argparse.ArgumentParser(description="dynamo_tpu message bus server")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=DEFAULT_PORT)
    p.add_argument(
        "--data-dir", default=None,
        help="enable work-queue durability (WAL + snapshot) in this directory",
    )
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    async def run():
        server = MessageBusServer(args.host, args.port, data_dir=args.data_dir)
        await server.start()
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":
    main()
