"""Cluster telemetry core: bounded time-series store + SLO burn-rate engine.

PR5's tracing plane answers "where did THIS request's time go"; the metrics
tiers answer "how is each process doing right now". Neither answers the
question an operator (or the ROADMAP-item-4 planner) actually asks: **"is
the service meeting its objectives, and if not, how fast is it failing?"**
This module is that layer, with zero dependencies and bounded memory:

- :class:`TimeSeries` — a fixed-interval ring of buckets per series
  (counter / gauge / histogram kinds). Writes are O(1); reads answer
  *windowed* queries (sum, rate, average, percentile, fraction-below-
  threshold) over any horizon the ring covers. Old buckets are reclaimed
  lazily in place — a series never grows past its ring.
- :class:`MetricStore` — named, labeled series with declared kinds, plus a
  JSON-able dump (the ``telemetry_dump`` RPC verb and ``GET /debug/slo``
  read it).
- :class:`Slo` / :class:`SloEngine` — declarative objectives ("95% of
  requests see TTFT ≤ 2 s over the slow window") evaluated with
  Google-SRE-style **multi-window burn rates**: the *page* signal needs the
  fast (5 m) AND mid (1 h) windows both burning ≥ ``burn_fast``×budget; the
  *ticket* signal is the slow (6 h) window alone ≥ ``burn_slow``× —
  deliberately single-window (where the SRE workbook pairs it with 30 m):
  budget spent is budget spent, so after recovery the page clears within
  the fast window while ``burning`` persists until the slow window drains.
  Fast windows catch a cliff within minutes; the mid-window guard keeps a
  single bad sample after a quiet night from paging; the slow window keeps
  a persistent trickle from hiding.

Windows and thresholds are env-tunable (``DYN_TPU_SLO_*``) with PR3-style
clamping — malformed, zero, or negative values fall back to defaults — so
tests (and staging) scale hours down to seconds without code changes.

Hot-path contract: with ``DYN_TPU_SLO=0`` every sampling helper returns
before allocating anything, same discipline as ``DYN_TPU_TRACE=0``
(asserted by ``tests/test_telemetry.py``). Clocks are injectable
(``clock=``) so the SLO math is deterministic under test.
"""

from __future__ import annotations

import json
import math
import os
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

# process birth, for dynamo_uptime_seconds on every exposition surface
PROCESS_START_MONOTONIC = time.monotonic()
PROCESS_START_WALL = time.time()

COUNTER = "counter"
GAUGE = "gauge"
HISTOGRAM = "histogram"

# latency histogram bounds in MILLISECONDS (the SLO engine's native unit;
# sub-ms decode gaps up to multi-minute pathologies)
DEFAULT_LATENCY_BOUNDS_MS = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0, 30000.0, 60000.0,
)


def uptime_seconds() -> float:
    return time.monotonic() - PROCESS_START_MONOTONIC


def build_info() -> Dict[str, str]:
    """Stable identity labels for ``dynamo_build_info`` (version skew across
    a fleet is the first thing to rule out in any incident)."""
    from dynamo_tpu import __version__

    jax_version = "absent"
    jax_mod = sys.modules.get("jax")
    if jax_mod is not None:
        jax_version = getattr(jax_mod, "__version__", "unknown")
    return {
        "version": __version__,
        "python": "%d.%d.%d" % sys.version_info[:3],
        "jax": jax_version,
    }


@dataclass
class TelemetryDump:
    """Wire type of the telemetry plane's poll surfaces: the reply of the
    aggregator's ``status`` endpoint and the ``telemetry_dump`` RPC verb
    (registered in ``llm/protocols`` ENDPOINT_PROTOCOLS — the request
    carries no payload, so the entry anchors this reply type)."""

    uptime_s: float = 0.0
    build: Dict[str, str] = field(default_factory=dict)
    enabled: bool = True
    series: Optional[dict] = None
    slo: Optional[list] = None
    cluster: Optional[dict] = None

    def to_dict(self) -> dict:
        out: Dict[str, Any] = {
            "uptime_s": self.uptime_s,
            "build": dict(self.build),
            "enabled": self.enabled,
        }
        if self.series is not None:
            out["series"] = self.series
        if self.slo is not None:
            out["slo"] = self.slo
        if self.cluster is not None:
            out["cluster"] = self.cluster
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "TelemetryDump":
        return cls(
            uptime_s=float(d.get("uptime_s", 0.0) or 0.0),
            build=dict(d.get("build") or {}),
            enabled=bool(d.get("enabled", True)),
            series=d.get("series"),
            slo=d.get("slo"),
            cluster=d.get("cluster"),
        )


class TelemetryPolicy:
    """The ``DYN_TPU_SLO_*`` knob bundle (PR3-style clamping).

    Window defaults follow the SRE-workbook sizes: page on 5 m + 1 h at
    14.4× budget burn; ticket on the 6 h window alone at 6× (single-window
    by design — see the module docstring). ``*_S`` knobs scale the windows
    (tests run the whole lifecycle in ~2 s); ``DYN_TPU_SLO_TTFT_MS`` /
    ``_ITL_MS`` move the latency objectives without redeploying.
    """

    __slots__ = (
        "enabled", "fast_window", "mid_window", "slow_window",
        "burn_fast", "burn_slow", "ttft_target_ms", "itl_target_ms",
    )

    def __init__(
        self,
        enabled: bool = True,
        fast_window: float = 300.0,
        mid_window: float = 3600.0,
        slow_window: float = 21600.0,
        burn_fast: float = 14.4,
        burn_slow: float = 6.0,
        ttft_target_ms: float = 2000.0,
        itl_target_ms: float = 100.0,
    ):
        self.enabled = bool(enabled)
        self.fast_window = max(float(fast_window), 1e-3)
        # windows must nest: a mid shorter than fast (or slow shorter than
        # mid) would make the confirmation window *less* data than the
        # signal it confirms
        self.mid_window = max(float(mid_window), self.fast_window)
        self.slow_window = max(float(slow_window), self.mid_window)
        self.burn_fast = float(burn_fast)
        self.burn_slow = float(burn_slow)
        self.ttft_target_ms = float(ttft_target_ms)
        self.itl_target_ms = float(itl_target_ms)

    @classmethod
    def from_env(cls, prefix: str = "DYN_TPU_SLO") -> "TelemetryPolicy":
        # shared knob parsers: the flag spelling set and the positive-float
        # clamping contract must stay identical across DYN_TPU_* planes
        from dynamo_tpu.runtime.admission import _env_pos_float
        from dynamo_tpu.runtime.tracing import _env_flag

        d = cls()
        return cls(
            enabled=_env_flag(prefix, d.enabled),
            fast_window=_env_pos_float(prefix + "_FAST_S", d.fast_window),
            mid_window=_env_pos_float(prefix + "_MID_S", d.mid_window),
            slow_window=_env_pos_float(prefix + "_SLOW_S", d.slow_window),
            burn_fast=_env_pos_float(prefix + "_BURN_FAST", d.burn_fast),
            burn_slow=_env_pos_float(prefix + "_BURN_SLOW", d.burn_slow),
            ttft_target_ms=_env_pos_float(prefix + "_TTFT_MS", d.ttft_target_ms),
            itl_target_ms=_env_pos_float(prefix + "_ITL_MS", d.itl_target_ms),
        )

    def ring_spec(self) -> Tuple[float, int]:
        """(bucket interval, bucket count) sized so the fast window has
        ~30 buckets of resolution and the ring still covers the slow
        window (plus one spare bucket for the in-progress edge)."""
        interval = self.fast_window / 30.0
        capacity = int(math.ceil(self.slow_window / interval)) + 2
        # bound the ring even under adversarial window ratios: 1 B users
        # don't need minute-resolution over a month in process memory
        return interval, min(capacity, 8192)


class TimeSeries:
    """One named series: a ring of fixed-interval buckets.

    Each slot stores ``(epoch, payload)`` where epoch identifies the
    absolute interval the slot currently represents; stale slots (lapped by
    the ring) are reinitialized on first touch — no background sweeper.

    Payloads by kind:
      counter    float sum of increments in the interval
      gauge      (count, sum, last) of samples in the interval
      histogram  (list[int] per-bound cumulative-style counts, count, sum)
                 — counts are per *series bounds*, NOT cumulative across
                 buckets; merging windows is element-wise addition.
    """

    __slots__ = (
        "name", "kind", "interval", "capacity", "bounds",
        "_epochs", "_data", "_lock", "clock",
    )

    def __init__(
        self,
        name: str,
        kind: str,
        interval: float,
        capacity: int,
        bounds: Optional[Sequence[float]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if kind not in (COUNTER, GAUGE, HISTOGRAM):
            raise ValueError(f"unknown series kind {kind!r}")
        self.name = name
        self.kind = kind
        self.interval = max(float(interval), 1e-6)
        self.capacity = max(int(capacity), 2)
        self.bounds: Tuple[float, ...] = tuple(bounds or ()) + (math.inf,)
        self._epochs = [-1] * self.capacity
        self._data: List[Any] = [None] * self.capacity
        self._lock = threading.Lock()
        self.clock = clock

    # -- write side ---------------------------------------------------------

    def _slot(self, t: Optional[float]) -> int:
        """Slot index for time ``t``, (re)initialized for this epoch."""
        now = self.clock() if t is None else t
        epoch = int(now // self.interval)
        i = epoch % self.capacity
        if self._epochs[i] != epoch:
            self._epochs[i] = epoch
            if self.kind == COUNTER:
                self._data[i] = 0.0
            elif self.kind == GAUGE:
                self._data[i] = [0, 0.0, 0.0]  # count, sum, last
            else:
                self._data[i] = [[0] * len(self.bounds), 0, 0.0]
        return i

    def inc(self, amount: float = 1.0, t: Optional[float] = None) -> None:
        with self._lock:
            i = self._slot(t)
            self._data[i] += amount

    def set(self, value: float, t: Optional[float] = None) -> None:
        with self._lock:
            i = self._slot(t)
            cell = self._data[i]
            cell[0] += 1
            cell[1] += value
            cell[2] = value

    def observe(self, value: float, t: Optional[float] = None) -> None:
        with self._lock:
            i = self._slot(t)
            counts, _, _ = self._data[i]
            for j, b in enumerate(self.bounds):
                if value <= b:
                    counts[j] += 1
                    break
            cell = self._data[i]
            cell[1] += 1
            cell[2] += value

    def observe_bucketed(
        self,
        delta_counts: Sequence[int],
        delta_sum: float = 0.0,
        t: Optional[float] = None,
    ) -> None:
        """Ingest pre-bucketed *per-bound* (non-cumulative) count deltas —
        how the cluster aggregator folds a worker's histogram snapshot diff
        into its own windowed series. Length mismatches are rejected
        (bounds drift between versions must not silently corrupt)."""
        if len(delta_counts) != len(self.bounds):
            raise ValueError(
                f"{self.name}: got {len(delta_counts)} bucket deltas for "
                f"{len(self.bounds)} bounds"
            )
        with self._lock:
            i = self._slot(t)
            counts, _, _ = self._data[i]
            total = 0
            for j, d in enumerate(delta_counts):
                d = int(d)
                if d > 0:
                    counts[j] += d
                    total += d
            cell = self._data[i]
            cell[1] += total
            cell[2] += float(delta_sum)

    # -- read side ----------------------------------------------------------

    def _live_cells(self, window: float, now: Optional[float]) -> List[Any]:
        now = self.clock() if now is None else now
        first_epoch = int((now - window) // self.interval)
        last_epoch = int(now // self.interval)
        first_epoch = max(first_epoch, last_epoch - self.capacity + 1)
        out = []
        with self._lock:
            for epoch in range(first_epoch, last_epoch + 1):
                i = epoch % self.capacity
                if self._epochs[i] == epoch and self._data[i] is not None:
                    out.append(self._data[i])
        return out

    def window_sum(self, window: float, now: Optional[float] = None) -> float:
        cells = self._live_cells(window, now)
        if self.kind == COUNTER:
            return float(sum(cells))
        if self.kind == GAUGE:
            return float(sum(c[1] for c in cells))
        return float(sum(c[2] for c in cells))

    def window_count(self, window: float, now: Optional[float] = None) -> int:
        cells = self._live_cells(window, now)
        if self.kind == COUNTER:
            return len(cells)
        return int(sum(c[0] if self.kind == GAUGE else c[1] for c in cells))

    def window_rate(self, window: float, now: Optional[float] = None) -> float:
        """Counter increments per second over the window."""
        return self.window_sum(window, now) / max(window, 1e-9)

    def window_avg(self, window: float, now: Optional[float] = None) -> float:
        """Mean of gauge samples (or histogram observations) in the window;
        0.0 when empty."""
        cells = self._live_cells(window, now)
        if self.kind == GAUGE:
            n = sum(c[0] for c in cells)
            return (sum(c[1] for c in cells) / n) if n else 0.0
        if self.kind == HISTOGRAM:
            n = sum(c[1] for c in cells)
            return (sum(c[2] for c in cells) / n) if n else 0.0
        return self.window_rate(window, now)

    def last(self) -> Optional[float]:
        """Most recent gauge sample, regardless of age (dashboards)."""
        with self._lock:
            newest, value = -1, None
            for epoch, cell in zip(self._epochs, self._data):
                if cell is not None and epoch > newest:
                    if self.kind == GAUGE:
                        if cell[0]:
                            newest, value = epoch, cell[2]
                    elif self.kind == COUNTER:
                        newest, value = epoch, float(cell)
        return value

    def _merged_counts(self, window: float, now: Optional[float]) -> Tuple[List[int], int]:
        merged = [0] * len(self.bounds)
        total = 0
        for counts, n, _ in self._live_cells(window, now):
            total += n
            for j, c in enumerate(counts):
                merged[j] += c
        return merged, total

    def window_percentile(
        self, q: float, window: float, now: Optional[float] = None
    ) -> Optional[float]:
        """Bucket-interpolated quantile over the window (None when empty)."""
        if self.kind != HISTOGRAM:
            raise TypeError(f"{self.name} is a {self.kind}, not a histogram")
        merged, total = self._merged_counts(window, now)
        if total == 0:
            return None
        rank = q * total
        prev_bound = 0.0
        cum = 0
        for bound, c in zip(self.bounds, merged):
            cum += c
            if cum >= rank:
                if math.isinf(bound):
                    return prev_bound  # clamp to last finite bound
                frac = (rank - (cum - c)) / c if c else 1.0
                return prev_bound + (bound - prev_bound) * frac
            if not math.isinf(bound):
                prev_bound = bound
        return prev_bound

    def window_fraction_le(
        self, threshold: float, window: float, now: Optional[float] = None
    ) -> Optional[float]:
        """Fraction of windowed samples ≤ threshold (the "good events" ratio
        of a latency SLO), interpolating within the straddling bucket.
        None when the window is empty — the caller decides what no data
        means (the SLO engine treats it as compliant: no traffic burns no
        budget)."""
        if self.kind != HISTOGRAM:
            raise TypeError(f"{self.name} is a {self.kind}, not a histogram")
        merged, total = self._merged_counts(window, now)
        if total == 0:
            return None
        good = 0.0
        prev_bound = 0.0
        for bound, c in zip(self.bounds, merged):
            if threshold >= bound:
                good += c
            else:
                if not math.isinf(bound) and threshold > prev_bound:
                    good += c * (threshold - prev_bound) / (bound - prev_bound)
                break
            if not math.isinf(bound):
                prev_bound = bound
        return min(good / total, 1.0)

    def dump(self, windows: Sequence[float]) -> dict:
        out: Dict[str, Any] = {"kind": self.kind}
        for w in windows:
            key = f"{w:g}s"
            if self.kind == COUNTER:
                out[key] = {"sum": self.window_sum(w), "rate": self.window_rate(w)}
            elif self.kind == GAUGE:
                out[key] = {"avg": self.window_avg(w), "last": self.last()}
            else:
                out[key] = {
                    "count": self.window_count(w),
                    "p50": self.window_percentile(0.50, w),
                    "p95": self.window_percentile(0.95, w),
                    "p99": self.window_percentile(0.99, w),
                }
        return out


class MetricStore:
    """Labeled series registry. ``series(name, **labels)`` creates on first
    use with the declared kind/bounds (default: gauge). One store per
    concern — the process-global edge store, one per cluster aggregator."""

    def __init__(
        self,
        policy: Optional[TelemetryPolicy] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.policy = policy or TelemetryPolicy.from_env()
        self.clock = clock
        self.interval, self.capacity = self.policy.ring_spec()
        self._declared: Dict[str, Tuple[str, Optional[Tuple[float, ...]]]] = {}
        self._series: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], TimeSeries] = {}
        self._lock = threading.Lock()

    def declare(
        self, name: str, kind: str, bounds: Optional[Sequence[float]] = None
    ) -> None:
        self._declared[name] = (kind, tuple(bounds) if bounds else None)

    def series(self, name: str, **labels: str) -> TimeSeries:
        key = (name, tuple(sorted((k, str(v)) for k, v in labels.items())))
        s = self._series.get(key)
        if s is None:
            with self._lock:
                s = self._series.get(key)
                if s is None:
                    kind, bounds = self._declared.get(name, (GAUGE, None))
                    if kind == HISTOGRAM and bounds is None:
                        bounds = DEFAULT_LATENCY_BOUNDS_MS
                    s = TimeSeries(
                        name, kind, self.interval, self.capacity,
                        bounds=bounds, clock=self.clock,
                    )
                    self._series[key] = s
        return s

    def labels_of(self, name: str) -> List[Dict[str, str]]:
        """Every label set seen for a series name (SLO fan-out per model)."""
        return [
            dict(lbls) for (n, lbls) in self._series.keys() if n == name
        ]

    def dump(self, windows: Optional[Sequence[float]] = None) -> dict:
        p = self.policy
        windows = windows or (p.fast_window, p.mid_window, p.slow_window)
        out: Dict[str, Any] = {}
        with self._lock:
            items = list(self._series.items())
        for (name, lbls), s in items:
            label_str = ",".join(f"{k}={v}" for k, v in lbls)
            out[f"{name}{{{label_str}}}" if label_str else name] = s.dump(windows)
        return out


# ---------------------------------------------------------------------------
# SLO model
# ---------------------------------------------------------------------------

# evaluation modes
LATENCY = "latency"        # histogram series + threshold: good = sample ≤ t
RATIO = "ratio"            # counter pair: good = 1 - bad/total
AVAILABILITY = "availability"  # gauge of 0/1 samples: good ratio = window avg


@dataclass(frozen=True)
class Slo:
    """One declarative objective.

    ``target`` is the good-event ratio (0.95 ⇒ "95% of events are good");
    the error budget is ``1 - target``. ``metric`` is the series holding
    the events; for :data:`RATIO` mode ``bad_metric`` holds the bad-event
    counter and ``metric`` the total. ``threshold`` (latency mode) is in
    the series' own unit (ms here).
    """

    name: str
    metric: str
    mode: str = LATENCY
    target: float = 0.95
    threshold: Optional[float] = None
    bad_metric: Optional[str] = None
    description: str = ""

    def good_ratio(
        self, store: MetricStore, window: float, labels: Dict[str, str],
        now: Optional[float] = None,
    ) -> Optional[float]:
        """Good-event fraction over the window; None = no data."""
        if self.mode == LATENCY:
            return store.series(self.metric, **labels).window_fraction_le(
                float(self.threshold or 0.0), window, now
            )
        if self.mode == RATIO:
            total = store.series(self.metric, **labels).window_sum(window, now)
            if total <= 0:
                return None
            bad = store.series(self.bad_metric or "", **labels).window_sum(
                window, now
            )
            return max(0.0, 1.0 - bad / total)
        if self.mode == AVAILABILITY:
            s = store.series(self.metric, **labels)
            if s.window_count(window, now) == 0:
                return None
            return s.window_avg(window, now)
        raise ValueError(f"unknown SLO mode {self.mode!r}")


def declare_standard_series(
    store_: MetricStore,
    latency_bounds: Optional[Sequence[float]] = None,
) -> MetricStore:
    """Declare the series the default SLO catalog reads. Every store that
    feeds a :class:`SloEngine` must run this (the global store and the
    cluster aggregator both do) — an undeclared series defaults to a gauge
    and a latency SLO would then query the wrong kind."""
    bounds = tuple(latency_bounds or DEFAULT_LATENCY_BOUNDS_MS)
    store_.declare("ttft_ms", HISTOGRAM, bounds=bounds)
    store_.declare("itl_ms", HISTOGRAM, bounds=bounds)
    store_.declare("requests_total", COUNTER)
    store_.declare("requests_errored", COUNTER)
    store_.declare("requests_shed", COUNTER)
    store_.declare("worker_available", GAUGE)
    return store_


def default_slos(policy: TelemetryPolicy) -> List[Slo]:
    """The serving SLO catalog (docs/observability.md §Cluster telemetry)."""
    return [
        Slo("ttft_p95", metric="ttft_ms", mode=LATENCY, target=0.95,
            threshold=policy.ttft_target_ms,
            description="95% of requests see first token within target"),
        Slo("itl_p95", metric="itl_ms", mode=LATENCY, target=0.95,
            threshold=policy.itl_target_ms,
            description="95% of inter-token gaps within target"),
        Slo("error_rate", metric="requests_total", mode=RATIO, target=0.999,
            bad_metric="requests_errored",
            description="99.9% of requests finish without error"),
        Slo("overload_share", metric="requests_total", mode=RATIO,
            target=0.99, bad_metric="requests_shed",
            description="≤1% of requests shed by admission control"),
        Slo("availability", metric="worker_available", mode=AVAILABILITY,
            target=0.99,
            description="99% of worker heartbeats healthy and serving"),
    ]


@dataclass
class SloStatus:
    """One SLO's evaluated state for one label set."""

    slo: str
    labels: Dict[str, str]
    target: float
    threshold: Optional[float]
    ratio_fast: Optional[float]
    ratio_slow: Optional[float]
    burn_fast: float
    burn_mid: float
    burn_slow: float
    # "ok" | "burning" (ticket: slow budget burning) | "alert" (page)
    state: str = "ok"
    compliant: bool = True

    def to_dict(self) -> dict:
        d = dict(self.__dict__)
        d["labels"] = dict(self.labels)
        return d


class SloEngine:
    """Evaluates a catalog of :class:`Slo` against a :class:`MetricStore`.

    Burn rate over a window W = (bad fraction over W) / error budget: 1.0
    means the budget is being spent exactly at the sustainable pace, 14.4
    means a 30-day budget dies in 2 days. Evaluation is pure (no background
    task): callers evaluate on render/dump, so a test with an injected
    clock is fully deterministic.
    """

    def __init__(
        self,
        store: MetricStore,
        policy: Optional[TelemetryPolicy] = None,
        slos: Optional[List[Slo]] = None,
        clock: Optional[Callable[[], float]] = None,
    ):
        self.store = store
        self.policy = policy or store.policy
        self.slos = list(slos) if slos is not None else default_slos(self.policy)
        self.clock = clock or store.clock

    def add(self, slo: Slo) -> None:
        self.slos.append(slo)

    def _burn(self, ratio: Optional[float], budget: float) -> float:
        if ratio is None:  # no traffic burns no budget
            return 0.0
        return (1.0 - ratio) / max(budget, 1e-9)

    def evaluate_one(self, slo: Slo, labels: Dict[str, str]) -> SloStatus:
        p = self.policy
        now = self.clock()
        budget = 1.0 - slo.target
        r_fast = slo.good_ratio(self.store, p.fast_window, labels, now)
        r_mid = slo.good_ratio(self.store, p.mid_window, labels, now)
        r_slow = slo.good_ratio(self.store, p.slow_window, labels, now)
        b_fast = self._burn(r_fast, budget)
        b_mid = self._burn(r_mid, budget)
        b_slow = self._burn(r_slow, budget)
        # multi-window: the page needs fast AND mid burning hot (the mid
        # confirmation keeps one bad sample after a quiet night from
        # paging). The ticket rides the slow window alone: budget spent is
        # budget spent, so after recovery the page clears within the fast
        # window but "burning" persists until the slow window drains —
        # the clear-after-slow-window semantics the e2e test asserts.
        if b_fast >= p.burn_fast and b_mid >= p.burn_fast:
            state = "alert"
        elif b_slow >= p.burn_slow:
            state = "burning"
        else:
            state = "ok"
        compliant = r_slow is None or r_slow >= slo.target
        return SloStatus(
            slo=slo.name,
            labels=dict(labels),
            target=slo.target,
            threshold=slo.threshold,
            ratio_fast=r_fast,
            ratio_slow=r_slow,
            burn_fast=round(b_fast, 3),
            burn_mid=round(b_mid, 3),
            burn_slow=round(b_slow, 3),
            state=state,
            compliant=compliant,
        )

    def evaluate(self) -> List[SloStatus]:
        out: List[SloStatus] = []
        for slo in self.slos:
            label_sets = self.store.labels_of(slo.metric) or [{}]
            for labels in label_sets:
                out.append(self.evaluate_one(slo, labels))
        return out

    def report(self) -> List[dict]:
        return [s.to_dict() for s in self.evaluate()]


# ---------------------------------------------------------------------------
# module-global state (per-process edge store + optional cluster aggregator)
# ---------------------------------------------------------------------------

_POLICY = TelemetryPolicy.from_env()
_STORE: Optional[MetricStore] = None
_ENGINE: Optional[SloEngine] = None
_CLUSTER: Optional[Any] = None  # ClusterTelemetry when an aggregator runs here
_LOCK = threading.Lock()


def configure(policy: Optional[TelemetryPolicy] = None) -> TelemetryPolicy:
    """(Re)build the global policy + store — tests call this after
    monkeypatching ``DYN_TPU_SLO_*``."""
    global _POLICY, _STORE, _ENGINE, _CLUSTER
    with _LOCK:
        _POLICY = policy or TelemetryPolicy.from_env()
        _STORE = None
        _ENGINE = None
        _CLUSTER = None
    return _POLICY


def enabled() -> bool:
    return _POLICY.enabled


def policy() -> TelemetryPolicy:
    return _POLICY


def store() -> MetricStore:
    global _STORE
    if _STORE is None:
        with _LOCK:
            if _STORE is None:
                _STORE = declare_standard_series(MetricStore(_POLICY))
    return _STORE


def slo_engine() -> SloEngine:
    global _ENGINE
    if _ENGINE is None:
        # resolve the store BEFORE taking the module lock: store() takes
        # the same non-reentrant lock, so calling it under _LOCK deadlocks
        # on the first slo_engine() call of a process that never touched
        # the store (the profiling lag_sampler/timeline shape)
        s = store()
        with _LOCK:
            if _ENGINE is None:
                _ENGINE = SloEngine(s, _POLICY)
    return _ENGINE


def set_cluster(cluster: Optional[Any]) -> None:
    """Register this process's cluster aggregator so the edge surfaces
    (``/debug/slo``, ``/metrics`` cluster section, ``telemetry_dump``)
    include the cluster view."""
    global _CLUSTER
    _CLUSTER = cluster


def cluster() -> Optional[Any]:
    return _CLUSTER


# -- sampling helpers (the only calls on hot-ish paths; all gated) ----------


def observe_latency(name: str, ms: float, **labels: str) -> None:
    """One latency sample into the process-global store (edge TTFT/ITL).
    Returns before allocating when sampling is disabled."""
    if not _POLICY.enabled:
        return
    store().series(name, **labels).observe(ms)


def count_request(outcome: str, **labels: str) -> None:
    """One finished edge request: outcome ``success`` | ``error`` |
    ``overloaded`` (matches the InflightGuard status labels)."""
    if not _POLICY.enabled:
        return
    store().series("requests_total", **labels).inc()
    if outcome == "overloaded":
        store().series("requests_shed", **labels).inc()
    elif outcome != "success":
        store().series("requests_errored", **labels).inc()


# -- exposition -------------------------------------------------------------


def render_process_info(extra_labels: Optional[Dict[str, str]] = None) -> str:
    """``dynamo_uptime_seconds`` + ``dynamo_build_info`` exposition lines
    (appended to every /metrics this process serves)."""
    from dynamo_tpu.llm.http.metrics import fmt_labels

    info = dict(build_info())
    if extra_labels:
        info.update(extra_labels)
    lines = [
        "# HELP dynamo_uptime_seconds Seconds since this process started",
        "# TYPE dynamo_uptime_seconds gauge",
        f"dynamo_uptime_seconds {uptime_seconds():.3f}",
        "# HELP dynamo_build_info Build/runtime identity (constant 1)",
        "# TYPE dynamo_build_info gauge",
        f"dynamo_build_info{fmt_labels(info)} 1",
    ]
    return "\n".join(lines) + "\n"


def render_cluster_metrics() -> str:
    """The cluster section for /metrics — empty when no aggregator is
    registered in this process."""
    c = _CLUSTER
    if c is None:
        return ""
    try:
        return c.render_prometheus()
    except Exception:  # cluster hiccups must never break /metrics
        return ""


def dump_state() -> dict:
    """Everything the ``telemetry_dump`` RPC verb / ``GET /debug/slo``
    return: process identity, the local store, the local SLO report, and —
    when an aggregator runs here — the cluster rollup + cluster SLOs."""
    out: Dict[str, Any] = {
        "uptime_s": round(uptime_seconds(), 3),
        "build": build_info(),
        "enabled": _POLICY.enabled,
    }
    if _POLICY.enabled:
        out["series"] = store().dump()
        out["slo"] = slo_engine().report()
    c = _CLUSTER
    if c is not None:
        try:
            out["cluster"] = c.dump()
        except Exception:
            out["cluster"] = {"error": "cluster dump failed"}
    return json.loads(json.dumps(out))  # ensure wire-safe plain types
