"""Control-plane blackout tolerance: the data plane outlives the statestore
and bus.

The reference architecture makes etcd the discovery authority and NATS the
metrics plane — both single points of failure. This module holds the policy
and shared machinery that turns a full control-plane outage into a degraded
*observability* event instead of a serving outage (docs/resilience.md
§Control-plane blackout):

- :class:`ControlPlanePolicy` — the ``DYN_TPU_*`` knob bundle (PR3 clamping
  contract) for stale-serve discovery, the disk discovery cache, rejoin
  jitter, cold-start deadline, and the bus publish buffer.
- :class:`ControlPlaneState` — process-global connected/stale/disconnected
  tracker per plane, exposed as the ``dynamo_control_plane_state`` gauge,
  the ``control_plane_state`` field on worker metric snapshots, the HTTP
  ``/health`` payload, and ``llmctl control-plane status``.
- :class:`DiscoveryCache` — an atomic on-disk snapshot of discovery
  prefixes (instances, model registry) so a frontend restarted *during* an
  outage cold-starts from the last-known-good view instead of hanging.
  Only constructed when ``DYN_TPU_DISCOVERY_CACHE`` names a directory —
  healthy fleets with the knob unset never touch disk (zero-overhead
  guard, tests/test_control_plane.py).
- :class:`BoundedPublishBuffer` — drop-oldest buffering for event-plane
  publishers during a bus outage; the telemetry aggregator's diff
  discipline absorbs the stamped backfill at recovery.
- :func:`rejoin_delay` — deterministic per-worker jitter so a fleet
  re-registering after a statestore recovery spreads its writes instead of
  thundering-herding the freshly restarted store.
- :class:`ControlPlaneUnavailable` — the typed cold-start failure: neither
  a reachable statestore nor a usable cache within the deadline. A
  ``ConnectionError`` subclass so pre-existing handlers keep working.

Design stance (docs/architecture.md): discovery is a *cache*, not an
authority. The statestore's word is advisory; the RPC-plane health probes
(runtime/health.py), which never depended on the store, are the liveness
authority whenever the two disagree.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, List, Optional, Tuple

CONNECTED = "connected"
STALE = "stale"
DISCONNECTED = "disconnected"

# numeric form for the dynamo_control_plane_state gauge; unknown states
# render as disconnected so a future state is never read as fine
STATE_VALUES = {CONNECTED: 0, STALE: 1, DISCONNECTED: 2}

ENV_CACHE = "DYN_TPU_DISCOVERY_CACHE"

# a lease lost within this many seconds of the owning client's store
# connection dropping is treated as OUTAGE-caused (the whole fleet lost
# leases together → rejoin jitter applies); a plain expiry on a client
# that was healthy throughout pays nothing
REJOIN_OUTAGE_WINDOW_S = 60.0


class ControlPlaneUnavailable(ConnectionError):
    """Cold start with neither a reachable statestore nor a usable
    discovery cache within the deadline. Typed so callers (and process
    supervisors) can distinguish "the control plane is down and I have
    nothing to serve from" from transient dial errors."""


# knob parsers live in the one shared home (runtime/envknobs.py); for the
# nonneg variants 0 is a policy (feature off), malformed/negative clamp
# to the default
from dynamo_tpu.runtime.envknobs import (  # noqa: E402
    env_flag as _env_flag,
    env_nonneg_float as _env_nonneg_float,
    env_nonneg_int as _env_nonneg_int,
    env_pos_float as _env_pos_float,
    env_str as _env_str,
)


@dataclass
class ControlPlanePolicy:
    """The blackout-tolerance knob bundle (``ControlPlanePolicy.from_env()``).

    ``stale_serve``        keep the last-known-good discovery view when the
                           statestore dies or restarts empty, and let the
                           RPC health probes govern liveness
                           (``DYN_TPU_STALE_SERVE``; 0 = the pre-blackout
                           behavior: the live set follows the store's word,
                           including clearing to empty).
    ``stale_grace``        seconds a stale discovery entry survives without
                           re-confirmation before the purge rules run
                           (``DYN_TPU_STALE_GRACE``; superseded or
                           probe-failed entries drop, probe-passing ones
                           are held — probes are the authority).
    ``rejoin_jitter``      max seconds of deterministic per-worker delay
                           before re-registering after a store *outage*
                           (``DYN_TPU_REJOIN_JITTER``; 0 = off). Plain
                           single-lease expiry never pays it.
    ``cold_start_deadline`` how long ``DistributedRuntime.create`` retries a
                           dead statestore before falling back to the cache
                           or raising :class:`ControlPlaneUnavailable`
                           (``DYN_TPU_COLD_START_DEADLINE``).
    ``bus_buffer``         entries a publisher buffers (drop-oldest) while
                           the bus is down (``DYN_TPU_BUS_BUFFER``; 0 = no
                           buffering, outage publishes are dropped as
                           before).
    ``cache_dir``          directory for the discovery snapshot
                           (``DYN_TPU_DISCOVERY_CACHE``; empty = cache off,
                           no file is ever opened).
    """

    stale_serve: bool = True
    stale_grace: float = 20.0
    rejoin_jitter: float = 5.0
    cold_start_deadline: float = 5.0
    bus_buffer: int = 256
    cache_dir: str = ""

    @classmethod
    def from_env(cls, prefix: str = "DYN_TPU_") -> "ControlPlanePolicy":
        d = cls()
        return cls(
            stale_serve=_env_flag(prefix + "STALE_SERVE", d.stale_serve),
            stale_grace=_env_pos_float(prefix + "STALE_GRACE", d.stale_grace),
            rejoin_jitter=_env_nonneg_float(
                prefix + "REJOIN_JITTER", d.rejoin_jitter
            ),
            cold_start_deadline=_env_pos_float(
                prefix + "COLD_START_DEADLINE", d.cold_start_deadline
            ),
            bus_buffer=_env_nonneg_int(prefix + "BUS_BUFFER", d.bus_buffer),
            cache_dir=_env_str(ENV_CACHE, d.cache_dir),
        )


def rejoin_delay(worker_id: str, window: float, seed: int = 0) -> float:
    """Deterministic jitter in ``[0, window)`` for one worker: a stable
    hash of ``(seed, worker_id)``, NOT process RNG — the same fleet
    recovering from the same outage always spreads the same way, so a
    recovery storm is replayable and testable. 100 workers re-registering
    after a blackout land spread across the window instead of inside one
    lease-TTL beat of each other."""
    if window <= 0:
        return 0.0
    digest = hashlib.sha256(f"{seed}:{worker_id}".encode()).digest()
    frac = int.from_bytes(digest[:8], "big") / float(1 << 64)
    return frac * window


# ---------------------------------------------------------------------------
# process-global state tracker
# ---------------------------------------------------------------------------


class ControlPlaneState:
    """Thread-safe connected/stale/disconnected view per plane.

    The statestore/bus clients report raw connectivity; discovery layers
    (EndpointClient, ModelWatcher) report how many entries they are
    currently serving on stale authority; publishers report buffered and
    dropped event counts. ``snapshot()`` folds all of it into the wire/
    exposition form."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._connected: Dict[str, bool] = {"statestore": True, "bus": True}
        self._since: Dict[str, float] = {}
        self._last_disconnect: Dict[str, float] = {}  # monotonic
        self._outages: Dict[str, int] = {"statestore": 0, "bus": 0}
        # discovery consumer id → count of entries currently held stale
        self._stale_entries: Dict[str, int] = {}
        # publisher id → events currently buffered awaiting the bus
        self._buffered: Dict[str, int] = {}
        self._dropped = 0
        # discovery views (instance sets, model registry) a consumer
        # cold-started from the disk cache — counted at each load, so one
        # frontend restart mid-outage counts once per seeded view
        self._cache_serves = 0

    def note_plane(self, plane: str, connected: bool) -> None:
        with self._lock:
            was = self._connected.get(plane, True)
            self._connected[plane] = connected
            if was and not connected:
                self._outages[plane] = self._outages.get(plane, 0) + 1
                self._since[plane] = time.time()
                self._last_disconnect[plane] = time.monotonic()
            elif connected:
                self._since.pop(plane, None)

    def seconds_since_disconnect(self, plane: str) -> float:
        """Monotonic seconds since this plane last lost its connection
        (``inf`` if it never has) — lets recovery paths distinguish
        "the store just came back from an outage" from "the store was
        healthy all along"."""
        with self._lock:
            t = self._last_disconnect.get(plane)
        return float("inf") if t is None else time.monotonic() - t

    def note_stale_entries(self, consumer: str, count: int) -> None:
        with self._lock:
            if count > 0:
                self._stale_entries[consumer] = count
            else:
                self._stale_entries.pop(consumer, None)

    def forget_consumer(self, consumer: str) -> None:
        with self._lock:
            self._stale_entries.pop(consumer, None)
            self._buffered.pop(consumer, None)

    def note_buffer(self, consumer: str, buffered: int,
                    dropped_delta: int = 0) -> None:
        with self._lock:
            if buffered > 0:
                self._buffered[consumer] = int(buffered)
            else:
                self._buffered.pop(consumer, None)
            self._dropped += max(int(dropped_delta), 0)

    def note_cache_serve(self) -> None:
        with self._lock:
            self._cache_serves += 1

    def plane_state(self, plane: str) -> str:
        with self._lock:
            return self._plane_state_locked(plane)

    def _plane_state_locked(self, plane: str) -> str:
        if not self._connected.get(plane, True):
            return DISCONNECTED
        if plane == "statestore" and sum(self._stale_entries.values()):
            # reconnected, but discovery still holds entries the store no
            # longer vouches for — the probes are mid-reconciliation
            return STALE
        if plane == "bus" and sum(self._buffered.values()):
            return STALE
        return CONNECTED

    def worst(self) -> str:
        with self._lock:
            states = [
                self._plane_state_locked(p) for p in ("statestore", "bus")
            ]
        return max(states, key=lambda s: STATE_VALUES.get(s, 2))

    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "state": CONNECTED,
                "stale_discovery_entries": sum(self._stale_entries.values()),
                "bus_buffered_events": sum(self._buffered.values()),
                "bus_dropped_events": self._dropped,
                "cache_cold_starts": self._cache_serves,
            }
            planes = {}
            for plane in ("statestore", "bus"):
                st = self._plane_state_locked(plane)
                entry = {"state": st, "outages": self._outages.get(plane, 0)}
                since = self._since.get(plane)
                if since is not None:
                    entry["down_for_s"] = round(time.time() - since, 1)
                planes[plane] = entry
            out["planes"] = planes
            out["state"] = max(
                (p["state"] for p in planes.values()),
                key=lambda s: STATE_VALUES.get(s, 2),
            )
            return out

    def reset(self) -> None:
        """Test hook: back to the everything-connected baseline."""
        with self._lock:
            self._connected = {"statestore": True, "bus": True}
            self._since.clear()
            self._last_disconnect.clear()
            self._outages = {"statestore": 0, "bus": 0}
            self._stale_entries.clear()
            self._buffered.clear()
            self._dropped = 0
            self._cache_serves = 0


_STATE = ControlPlaneState()


def state() -> ControlPlaneState:
    return _STATE


def note_store(connected: bool) -> None:
    _STATE.note_plane("statestore", connected)


def note_bus(connected: bool) -> None:
    _STATE.note_plane("bus", connected)


def snapshot() -> dict:
    return _STATE.snapshot()


def state_name() -> str:
    """Worst plane state, the wire form workers publish."""
    return _STATE.worst()


def reset_for_tests() -> None:
    _STATE.reset()


def render_prometheus(prefix: str = "dynamo") -> str:
    """The ``dynamo_control_plane_state`` gauge (0=connected, 1=stale,
    2=disconnected, labeled per plane) plus the bus buffer counters —
    appended to whatever exposition the process already serves."""
    snap = _STATE.snapshot()
    full = f"{prefix}_control_plane_state"
    lines = [
        f"# HELP {full} Control-plane connectivity "
        f"(0=connected, 1=stale, 2=disconnected)",
        f"# TYPE {full} gauge",
    ]
    for plane, entry in sorted(snap["planes"].items()):
        lines.append(
            f'{full}{{plane="{plane}"}} '
            f'{STATE_VALUES.get(entry["state"], 2)}'
        )
    for name, key, help_text in (
        ("control_plane_buffered_events", "bus_buffered_events",
         "Events buffered while the bus is unreachable"),
        ("control_plane_dropped_events", "bus_dropped_events",
         "Events dropped from the full outage buffer (cumulative)"),
        ("control_plane_stale_discovery_entries", "stale_discovery_entries",
         "Discovery entries currently served on stale authority"),
    ):
        full = f"{prefix}_{name}"
        lines.append(f"# HELP {full} {help_text}")
        lines.append(f"# TYPE {full} gauge")
        lines.append(f"{full} {snap[key]}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# disk-persisted discovery snapshot
# ---------------------------------------------------------------------------


class DiscoveryCache:
    """Atomic per-prefix JSON snapshots of discovery state.

    One file per watched prefix (instances of an endpoint, the model
    registry) so concurrent writers never contend on one file. Values are
    the raw statestore bytes, base64-wrapped; a corrupt or unreadable file
    reads as "no cache" — a bad snapshot must degrade to the no-cache path,
    never crash a cold start."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, prefix: str) -> str:
        digest = hashlib.sha256(prefix.encode()).hexdigest()[:16]
        return os.path.join(self.root, f"discovery-{digest}.json")

    def save(self, prefix: str, entries: Dict[str, bytes]) -> None:
        """Synchronous write (call via ``asyncio.to_thread`` from async
        code); tmp + rename so readers never see a torn file."""
        out = {
            "prefix": prefix,
            "saved_at": time.time(),
            "entries": {
                k: base64.b64encode(v).decode() for k, v in entries.items()
            },
        }
        path = self._path(prefix)
        # unique per write: two same-process writers of one prefix (e.g.
        # a model's chat and completions clients) must not interleave into
        # one tmp file and install a torn snapshot
        tmp = f"{path}.tmp.{os.getpid()}.{uuid.uuid4().hex[:8]}"
        with open(tmp, "w") as f:
            json.dump(out, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def load(self, prefix: str) -> Optional[Dict[str, bytes]]:
        """The cached entries for a prefix, or None when absent/corrupt."""
        path = self._path(prefix)
        if not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                raw = json.load(f)
            if raw.get("prefix") != prefix:
                return None  # digest collision or hand-edited file
            return {
                k: base64.b64decode(v)
                for k, v in raw.get("entries", {}).items()
            }
        except (json.JSONDecodeError, OSError, ValueError, TypeError):
            return None

    def saved_at(self, prefix: str) -> Optional[float]:
        path = self._path(prefix)
        try:
            with open(path) as f:
                return float(json.load(f).get("saved_at", 0.0))
        except (OSError, json.JSONDecodeError, ValueError, TypeError):
            return None

    def has_any(self) -> bool:
        try:
            return any(
                n.startswith("discovery-") and n.endswith(".json")
                for n in os.listdir(self.root)
            )
        except OSError:
            return False


def maybe_cache(
    policy: Optional[ControlPlanePolicy] = None,
) -> Optional[DiscoveryCache]:
    """The gate every discovery path uses: ``None`` (and therefore zero
    file IO, one None-check per hot-path site) unless
    ``DYN_TPU_DISCOVERY_CACHE`` names a directory."""
    root = (
        policy.cache_dir if policy is not None
        else _env_str(ENV_CACHE, "")
    )
    return DiscoveryCache(root) if root else None


# ---------------------------------------------------------------------------
# bounded outage buffering for event-plane publishers
# ---------------------------------------------------------------------------


class BoundedPublishBuffer:
    """Drop-oldest buffer for payloads that could not be published.

    Each entry remembers when it was produced so the flush can stamp
    ``stale_s`` — consumers (the telemetry aggregator, planner sources)
    see exactly how old a backfilled snapshot is instead of mistaking it
    for fresh data. ``dropped`` counts evictions cumulatively."""

    def __init__(self, capacity: int):
        self.capacity = max(int(capacity), 1)
        self._dq: Deque[Tuple[float, object]] = deque()
        self.dropped = 0

    def __len__(self) -> int:
        return len(self._dq)

    def push(self, payload: object, age_s: float = 0.0) -> None:
        """``age_s`` back-dates the entry — a re-buffered item that already
        waited through a failed flush must keep its true age, not restart
        the staleness clock."""
        if len(self._dq) >= self.capacity:
            self._dq.popleft()
            self.dropped += 1
        self._dq.append((time.monotonic() - max(age_s, 0.0), payload))

    def drain(self) -> List[Tuple[float, object]]:
        """All buffered (age_s, payload) pairs, oldest first; the buffer
        empties. Callers re-``push`` whatever fails to flush."""
        now = time.monotonic()
        out = [(now - t, p) for t, p in self._dq]
        self._dq.clear()
        return out
