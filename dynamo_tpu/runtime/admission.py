"""Worker admission control: bound the pending queue, shed overload early.

PR 2 made the request path survive *dead* workers; this module makes it
survive *busy* ones. Without it a traffic spike queues unboundedly inside
every worker — memory grows, every queued request eventually times out, and
the failure mode is cascading timeouts instead of fast, bounded degradation.

Three cooperating pieces, all env-tunable via ``DYN_TPU_ADMIT_*``:

- :class:`AdmissionPolicy` — the knob bundle: pending-queue bound, optional
  KV-block floor, retry-hint base, and the per-stream send-queue cap +
  slow-consumer bound used by ``runtime/rpc.py``'s backpressure layer.
- :class:`AdmissionController` — the per-worker gate. ``try_admit`` checks
  the live pending count (RPC in-flight tasks) and, when the serving engine
  exposes capacity (``engine_jax`` free decode slots + free KV blocks from
  ``engine_jax/allocator.py``), the engine's headroom. Over-budget requests
  are answered with a typed, *retryable* ``OVERLOADED`` reply carrying the
  queue depth and a ``retry_after_ms`` hint — they never silently queue.
- :class:`LoadSnapshot` — the compact load view workers piggyback on RPC
  replies and statestore instance-key heartbeats; routers use it to pick the
  least-loaded live instance and to stop dispatching to draining workers.

Reference analogue: the dynamo_tpu paper's KV-cache-aware router routes on
capacity signals published by workers; here the same signals also gate
admission at the worker so a router with a stale view cannot overrun it.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

from dynamo_tpu.runtime.resilience import RetryableRpcError

# Canonical message prefix for overload errors crossing process boundaries
# (mirrors resilience.DEADLINE_ERROR); the HTTP edge maps it to 429.
OVERLOAD_ERROR = "overloaded"


class OverloadedError(RetryableRpcError):
    """A worker shed the request before doing any work (queue full / no KV
    headroom / tenant over its rate quota). Retryable by design — another
    instance may have capacity — but it must NOT trip the circuit breaker:
    the worker is healthy, just busy, and ejecting it would amplify the
    overload on its siblings. Soft-eject (avoid it for ``retry_after_ms``)
    instead. ``tenant`` is set when the shed was a per-tenant rate limit
    (``runtime/qos.py``) — that retry hint is the tenant's own bucket
    refill, so failover to a sibling would just burn its bucket there too.
    """

    def __init__(self, message: str, queue_depth: int = 0,
                 retry_after_ms: int = 0, tenant: Optional[str] = None):
        super().__init__(message)
        self.queue_depth = queue_depth
        self.retry_after_ms = retry_after_ms
        self.tenant = tenant
        # the snapshot the gate decided on (worker side only; not wired) —
        # lets the shed reply reuse it instead of probing the engine twice
        self.load: Optional[LoadSnapshot] = None


class SlowConsumer(ConnectionError):
    """A stream's reader stopped draining tokens for longer than the
    slow-consumer bound while its bounded send queue was full. The stream
    is cut (context killed) so worker memory stays bounded."""


@dataclass
class LoadSnapshot:
    """Compact per-worker load view (wire form is short-keyed JSON).

    ``queue_depth`` counts requests the worker has accepted but not
    finished beyond its engine slots (RPC in-flight + engine waiting);
    ``active_slots``/``total_slots`` and the KV block counters come from
    the engine when it exposes capacity, and stay 0/0 for engines that
    don't (routers then fall back to queue depth alone).
    """

    active_slots: int = 0
    total_slots: int = 0
    queue_depth: int = 0
    kv_free_blocks: int = 0
    kv_total_blocks: int = 0
    draining: bool = False
    # health-plane state (runtime/health.py): "healthy" | "degraded" |
    # "unhealthy"; routers skip unhealthy instances like draining ones
    health: str = "healthy"

    def utilization(self) -> float:
        """Scalar load score for least-loaded routing (lower = freer).

        Slot occupancy plus queue pressure plus KV pressure; engines
        without capacity reporting contribute queue depth only (scaled so
        one queued request ≈ one busy slot on an 8-slot worker)."""
        score = 0.0
        if self.total_slots > 0:
            score += self.active_slots / self.total_slots
            score += self.queue_depth / self.total_slots
        else:
            score += self.queue_depth / 8.0
        if self.kv_total_blocks > 0:
            score += 1.0 - (self.kv_free_blocks / self.kv_total_blocks)
        return score

    def to_wire(self) -> dict:
        out: Dict[str, Any] = {"q": self.queue_depth}
        if self.total_slots:
            out["s"] = self.active_slots
            out["S"] = self.total_slots
        if self.kv_total_blocks:
            out["kf"] = self.kv_free_blocks
            out["kt"] = self.kv_total_blocks
        if self.draining:
            out["d"] = 1
        if self.health != "healthy":
            out["h"] = self.health
        return out

    @classmethod
    def from_wire(cls, d: dict) -> "LoadSnapshot":
        try:
            return cls(
                active_slots=int(d.get("s", 0)),
                total_slots=int(d.get("S", 0)),
                queue_depth=int(d.get("q", 0)),
                kv_free_blocks=int(d.get("kf", 0)),
                kv_total_blocks=int(d.get("kt", 0)),
                draining=bool(d.get("d", 0)),
                health=str(d.get("h", "healthy")),
            )
        except (TypeError, ValueError):
            return cls()


# knob parsers live in the one shared home (runtime/envknobs.py): a bad
# value must degrade to sane behavior, never to an admission gate that
# rejects everything (0) or admits everything (negative as unbounded)
from dynamo_tpu.runtime.envknobs import (  # noqa: E402
    env_nonneg_int as _env_nonneg_int,
    env_pos_float as _env_pos_float,
    env_pos_int as _env_pos_int,
)


@dataclass
class AdmissionPolicy:
    """Per-worker overload knobs (``AdmissionPolicy.from_env()``).

    ``max_pending``          hard bound on concurrently accepted requests
                             (engine slots + queued); above it, shed.
    ``min_free_kv_blocks``   shed token-bearing requests when the engine's
                             free KV blocks drop below this floor
                             (0 = disabled; engines without an allocator
                             are never KV-gated).
    ``retry_after_ms``       base client back-off hint on a shed; scaled by
                             how far over budget the queue is.
    ``send_queue_cap``       per-stream bounded send queue in the RPC
                             server — a slow reader backpressures the
                             generator instead of buffering tokens.
    ``slow_consumer_timeout``  how long a stream's send queue may stay full
                             before the stream is cut as a slow consumer.
    """

    max_pending: int = 64
    min_free_kv_blocks: int = 0
    retry_after_ms: int = 200
    send_queue_cap: int = 32
    slow_consumer_timeout: float = 30.0

    @classmethod
    def from_env(cls, prefix: str = "DYN_TPU_ADMIT_") -> "AdmissionPolicy":
        d = cls()
        return cls(
            max_pending=_env_pos_int(prefix + "MAX_PENDING", d.max_pending),
            min_free_kv_blocks=_env_nonneg_int(
                prefix + "MIN_FREE_KV_BLOCKS", d.min_free_kv_blocks
            ),
            retry_after_ms=_env_pos_int(prefix + "RETRY_AFTER_MS", d.retry_after_ms),
            send_queue_cap=_env_pos_int(prefix + "SEND_QUEUE", d.send_queue_cap),
            slow_consumer_timeout=_env_pos_float(
                prefix + "SLOW_CONSUMER_TIMEOUT", d.slow_consumer_timeout
            ),
        )


class AdmissionController:
    """The per-worker admission gate + load snapshot source.

    ``engine_probe`` (optional) returns the serving engine's capacity dict
    (``metrics_snapshot()`` shape: request_active_slots / request_total_slots
    / kv_active_blocks / kv_total_blocks / num_requests_waiting); without it
    the gate bounds the RPC pending count alone.
    """

    def __init__(
        self,
        policy: Optional[AdmissionPolicy] = None,
        engine_probe: Optional[Callable[[], Dict[str, Any]]] = None,
        qos: Optional[Any] = None,
    ):
        self.policy = policy or AdmissionPolicy.from_env()
        self.engine_probe = engine_probe
        self.admitted = 0
        # capacity sheds ONLY (queue/KV pressure): this feeds the
        # overload_share SLO. Tenant rate sheds count separately below —
        # a correctly-throttled abuser is the QoS plane working, and it
        # must not page the capacity SLO on a healthy fleet.
        self.shed = 0
        self.rate_limited = 0
        self.slow_consumer_cuts = 0
        # multi-tenant QoS (runtime/qos.py): per-tenant token buckets.
        # Built only when tenant knobs are set AND a rate is configured —
        # the single-tenant hot path pays exactly one None-check.
        from dynamo_tpu.runtime import qos as qos_mod

        self.qos = qos if qos is not None else qos_mod.maybe_from_env()
        self.tenant_limiter = (
            qos_mod.TenantRateLimiter(self.qos)
            if self.qos is not None and self.qos.rate_rps > 0
            else None
        )

    def _engine_state(self) -> Dict[str, Any]:
        if self.engine_probe is None:
            return {}
        try:
            return self.engine_probe() or {}
        except Exception:  # a broken probe must not take down admission
            return {}

    def snapshot(self, pending: int, draining: bool = False) -> LoadSnapshot:
        es = self._engine_state()
        total_blocks = int(es.get("kv_total_blocks", 0) or 0)
        # prefer the engine's own free count (engine_jax reports it, and it
        # correctly counts reclaimable cached blocks as free); fall back to
        # total − active for engines that only publish the generic pair
        if "kv_free_blocks" in es:
            free_blocks = int(es.get("kv_free_blocks", 0) or 0)
        else:
            free_blocks = max(total_blocks - int(es.get("kv_active_blocks", 0) or 0), 0)
        active = int(es.get("request_active_slots", 0) or 0)
        total_slots = int(es.get("request_total_slots", 0) or 0)
        waiting = int(es.get("num_requests_waiting", 0) or 0)
        # ``pending`` (RPC in-flight) already contains both the requests
        # holding engine slots and the engine-queued ones; queue_depth is
        # the excess beyond the slots, not a double count. The engine's own
        # waiting figure wins when larger (requests can enter it by
        # non-RPC paths, e.g. remote prefill).
        if total_slots > 0:
            queue = max(pending - active, waiting, 0)
        else:
            queue = pending
        return LoadSnapshot(
            active_slots=active,
            total_slots=total_slots,
            queue_depth=queue,
            kv_free_blocks=free_blocks,
            kv_total_blocks=total_blocks,
            draining=draining,
        )

    def retry_after_ms(self, snap: LoadSnapshot) -> int:
        """Back-off hint scaled by overshoot: the deeper the queue relative
        to the budget, the longer the hint (capped at 5s)."""
        base = self.policy.retry_after_ms
        over = snap.queue_depth / max(self.policy.max_pending, 1)
        return min(int(base * (1.0 + over)), 5_000)

    def tenant_stats(self) -> Dict[str, Dict[str, int]]:
        """Cumulative per-tenant admit/rate-limit counters (telemetry);
        empty when tenant rate limiting is off."""
        if self.tenant_limiter is None:
            return {}
        return self.tenant_limiter.stats()

    def try_admit(
        self, pending: int, tenant: Optional[str] = None
    ) -> Optional[OverloadedError]:
        """Admit or shed one incoming request given ``pending`` already
        accepted. Returns None when admitted, or the typed error to reply
        with when shed (the caller formats the wire reply).

        The global gates run first (they are pure reads); the per-tenant
        token is consumed only for requests the worker could actually
        take — a globally-shed retry storm must not burn an innocent
        tenant's quota (or inflate its ``admitted`` stat). The isolation
        contract still holds: a 10×-quota flood that passes the global
        gates is rate-shed here, with the tenant's OWN bucket refill as
        the retry hint, and never occupies the shared queue."""
        snap = self.snapshot(pending)
        err: Optional[OverloadedError] = None
        if pending >= self.policy.max_pending:
            err = OverloadedError(
                f"{OVERLOAD_ERROR}: pending queue full "
                f"({pending}/{self.policy.max_pending})",
                queue_depth=snap.queue_depth,
                retry_after_ms=self.retry_after_ms(snap),
            )
        elif (
            self.policy.min_free_kv_blocks > 0
            and snap.kv_total_blocks > 0
            and snap.kv_free_blocks < self.policy.min_free_kv_blocks
        ):
            err = OverloadedError(
                f"{OVERLOAD_ERROR}: KV pressure "
                f"({snap.kv_free_blocks} free blocks < "
                f"{self.policy.min_free_kv_blocks} floor)",
                queue_depth=snap.queue_depth,
                retry_after_ms=self.retry_after_ms(snap),
            )
        if err is not None:
            self.shed += 1
            err.load = snap
            return err
        if self.tenant_limiter is not None:
            wait_s = self.tenant_limiter.take(tenant)
            if wait_s > 0:
                t = tenant or "default"
                err = OverloadedError(
                    f"{OVERLOAD_ERROR}: tenant {t!r} over rate quota",
                    queue_depth=snap.queue_depth,
                    retry_after_ms=min(int(wait_s * 1000) + 1, 60_000),
                    tenant=t,
                )
                # NOT self.shed: tenant throttling has its own signal
                # (dynamo_tenant_rate_limited_total + llmctl tenant
                # status exit 2) and must not page overload_share
                self.rate_limited += 1
                err.load = snap
                return err
        self.admitted += 1
        return None
