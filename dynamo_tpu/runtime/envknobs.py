"""The one shared home for ``DYN_TPU_*`` env-knob parsing.

Every knob bundle in the tree (admission, resilience, qos, tracing,
integrity, profiling, control-plane, migration) follows the same PR3
clamping contract: a malformed, out-of-range, or nonsensical value must
degrade to the documented default — never to a surprise policy the
operator didn't ask for (an admission gate that rejects everything, an
unbounded ring, a disabled integrity plane). The parsers used to be
copied per module; dynlint's ``knob-discipline`` rule now points every
raw ``os.environ`` read of a ``DYN_TPU_*`` name here instead, and
``dynlint --list-knobs`` builds the knob catalog from calls into this
module (plus the per-bundle wrappers), cross-checked against the knob
tables in ``docs/*.md``.

Semantics, by helper:

====================  ======================================================
``env_raw``           raw optional string; empty string counts as unset
``env_str``           non-empty string or the default
``env_flag``          unset → default; "0"/"false"/"no"/"off" (any case) →
                      False; anything else → True
``env_pos_int``       > 0 or the default (0 and negatives are misconfigs)
``env_nonneg_int``    >= 0 or the default (0 is a *policy*, e.g. "off")
``env_pos_float``     > 0 or the default
``env_nonneg_float``  >= 0 or the default
``env_opt_pos_float`` > 0, or None for unset/<= 0 (a disabled deadline)
``env_clamped_int``   > 0 clamped into [lo, hi], else the default
``env_clamped_float`` > 0 clamped into [lo, hi], else the default
====================  ======================================================
"""

from __future__ import annotations

import os
from typing import Optional

_FALSY = ("0", "false", "no", "off")


def env_raw(name: str, default: Optional[str] = None) -> Optional[str]:
    """Free-form knob (paths, URLs, fault specs): the raw value, with the
    empty string treated as unset."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    return raw


def env_str(name: str, default: str) -> str:
    raw = os.environ.get(name)
    return raw if raw else default


def env_flag(name: str, default: bool) -> bool:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    return raw.strip().lower() not in _FALSY


def env_pos_int(name: str, default: int) -> int:
    """Positive-int knob: unset, malformed, zero, or negative → default —
    a bad value must degrade to sane behavior, never to a gate that
    rejects everything (0) or a bound of -1."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        v = int(raw)
    except ValueError:
        return default
    return v if v > 0 else default


def env_nonneg_int(name: str, default: int) -> int:
    """Like :func:`env_pos_int` but ``0`` is a *policy*, not a misconfig
    (``DYN_TPU_RESUME=0`` = resume off); only malformed or negative
    values clamp to the default."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        v = int(raw)
    except ValueError:
        return default
    return v if v >= 0 else default


def env_pos_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        v = float(raw)
    except ValueError:
        return default
    return v if v > 0 else default


def env_nonneg_float(name: str, default: float) -> float:
    """Non-negative float knob (0 is a meaningful 'disabled' value)."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        v = float(raw)
    except ValueError:
        return default
    return v if v >= 0 else default


def env_opt_pos_float(
    name: str, default: Optional[float]
) -> Optional[float]:
    """Optional positive float: unset/malformed → default, <= 0 → None
    (an explicitly disabled deadline/budget)."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        v = float(raw)
    except ValueError:
        return default
    return None if v <= 0 else v


def env_clamped_int(name: str, default: int, lo: int, hi: int) -> int:
    """Positive-int knob clamped into [lo, hi]; malformed or non-positive
    values fall back to the default."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        v = int(raw)
    except ValueError:
        return default
    if v <= 0:
        return default
    return min(max(v, lo), hi)


def env_clamped_float(
    name: str, default: float, lo: float, hi: float
) -> float:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        v = float(raw)
    except ValueError:
        return default
    if v <= 0:
        return default
    return min(max(v, lo), hi)
