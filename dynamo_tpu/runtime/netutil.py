"""Shared asyncio server plumbing.

`TrackedServer` wraps asyncio.start_server with connection tracking so stop()
can force-close lingering client connections — Python 3.12's
Server.wait_closed() otherwise blocks until every client hangs up on its own.
Used by the statestore, message bus, rpc and kv-transfer servers.
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable, Optional

Handler = Callable[[asyncio.StreamReader, asyncio.StreamWriter], Awaitable[None]]


class TrackedServer:
    def __init__(self, handler: Handler, host: str, port: int):
        self.handler = handler
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: set = set()

    async def start(self) -> int:
        """Start listening; returns the bound port."""

        async def handle(reader, writer):
            self._conns.add(writer)
            try:
                await self.handler(reader, writer)
            finally:
                self._conns.discard(writer)

        self._server = await asyncio.start_server(handle, self.host, self.port)
        if self.port == 0:
            self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    def close_listener(self) -> None:
        """Stop accepting new connections (existing ones keep running)."""
        if self._server:
            self._server.close()

    async def stop(self) -> None:
        if self._server:
            self._server.close()
            # a reconnecting client can race the listener close and land a
            # fresh connection AFTER the first force-close sweep — keep
            # sweeping until the set drains, and never block stop() forever
            # on a wedged handler
            for _ in range(100):
                for w in list(self._conns):
                    w.close()
                if not self._conns:
                    break
                await asyncio.sleep(0.02)
            try:
                await asyncio.wait_for(self._server.wait_closed(), 5.0)
            except asyncio.TimeoutError:
                pass
