"""In-memory mock transport with latency models for tests.

Reference parity: `lib/runtime/tests/common/mock.rs:31-496` — a complete fake
network transport (`MockNetworkTransport::new_egress_ingress`) with
`LatencyModel::{NoDelay, ConstantDelayInNanos, NormalDistribution}` so
multi-node pipelines and routing policies are unit-testable without a
cluster, and latency-sensitivity regressions are visible in CI.

TPU-build shape: the seam is :class:`AsyncEngine` (every network hop proxies
one), so the mock is an engine wrapper pair —

- :class:`MockNetwork` — a registry standing in for discovery: register
  engines under endpoint names, get back latency-injected clients.
- :class:`MockChannel` — the egress↔ingress pair for ONE endpoint: applies
  the request-path latency before dispatch, the response-path latency per
  item, counts in-flight requests, and injects faults (connection errors,
  drops) on demand.
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass
from typing import AsyncIterator, Dict, Optional

from dynamo_tpu.runtime.annotated import Annotated
from dynamo_tpu.runtime.engine import AsyncEngine, Context


# -- latency models ----------------------------------------------------------


class LatencyModel:
    """Base: no delay (reference LatencyModel::NoDelay)."""

    async def delay(self) -> None:
        return None


class NoDelay(LatencyModel):
    pass


@dataclass
class ConstantDelay(LatencyModel):
    """Fixed delay per hop (reference ConstantDelayInNanos)."""

    seconds: float

    async def delay(self) -> None:
        if self.seconds > 0:
            await asyncio.sleep(self.seconds)


@dataclass
class NormalDistribution(LatencyModel):
    """Gaussian delay, clamped at ``floor`` (reference NormalDistribution)."""

    mean: float
    std: float
    floor: float = 0.0
    seed: Optional[int] = None

    def __post_init__(self):
        self._rng = random.Random(self.seed)

    async def delay(self) -> None:
        d = max(self.floor, self._rng.gauss(self.mean, self.std))
        if d > 0:
            await asyncio.sleep(d)


# -- the egress/ingress pair -------------------------------------------------


class MockChannel(AsyncEngine):
    """Latency-injecting in-memory proxy in front of one engine.

    The request path sleeps ``request_latency`` once (the NATS push + TCP
    connect-back of the real plane); the response path sleeps
    ``response_latency`` before each item (per-frame transit). Faults:
    ``fail_next(n)`` makes the next n requests surface a connection error
    (as an error item, exactly like the real egress does)."""

    def __init__(
        self,
        engine: AsyncEngine,
        request_latency: Optional[LatencyModel] = None,
        response_latency: Optional[LatencyModel] = None,
    ):
        self.engine = engine
        self.request_latency = request_latency or NoDelay()
        self.response_latency = response_latency or NoDelay()
        self.inflight = 0
        self.total_requests = 0
        self._fail_budget = 0

    def fail_next(self, n: int = 1) -> None:
        self._fail_budget += n

    async def generate(self, request: Context) -> AsyncIterator[Annotated]:
        self.total_requests += 1
        if self._fail_budget > 0:
            self._fail_budget -= 1
            yield Annotated.from_error("mock transport: connection refused")
            return
        await self.request_latency.delay()
        self.inflight += 1
        try:
            async for item in self.engine.generate(request):
                await self.response_latency.delay()
                if request.context.is_stopped:
                    return  # egress stops reading when the caller cancels
                yield item
        finally:
            self.inflight -= 1


class MockNetwork:
    """Stand-in for the discovery plane: endpoint name → engine, with a
    network-wide default latency model and per-endpoint overrides."""

    def __init__(
        self,
        request_latency: Optional[LatencyModel] = None,
        response_latency: Optional[LatencyModel] = None,
    ):
        self.request_latency = request_latency or NoDelay()
        self.response_latency = response_latency or NoDelay()
        self._endpoints: Dict[str, AsyncEngine] = {}
        self._channels: Dict[str, MockChannel] = {}

    def register(self, name: str, engine: AsyncEngine) -> None:
        self._endpoints[name] = engine

    def endpoints(self) -> list:
        return sorted(self._endpoints)

    def client(
        self,
        name: str,
        request_latency: Optional[LatencyModel] = None,
        response_latency: Optional[LatencyModel] = None,
    ) -> MockChannel:
        """An egress client for an endpoint (one channel per endpoint,
        reused — its counters accumulate like a real connection's)."""
        if name not in self._endpoints:
            raise KeyError(f"unknown mock endpoint {name!r}")
        ch = self._channels.get(name)
        if ch is None:
            ch = self._channels[name] = MockChannel(
                self._endpoints[name],
                request_latency or self.request_latency,
                response_latency or self.response_latency,
            )
        return ch
