"""Composable request/response pipelines.

A pipeline is a chain of :class:`Operator` stages ending in an :class:`AsyncEngine`.
Requests flow forward through each operator (which may transform them); the
response stream flows backward through the same operators (which may transform
each item). Because a network client is itself an AsyncEngine, a pipeline can be
cut at any point and its tail served in another process — the frontend half ends
in the client engine, the backend half is served behind a network ingress.

Reference parity: dynamo's pipeline graph — `Source`/`Sink`/`Operator`,
`ServiceFrontend`, `SegmentSource/Sink`, `ServiceBackend`, `.link()`
(lib/runtime/src/pipeline/nodes.rs:48-351). The TPU build collapses the
node/edge machinery into one functional composition: an operator receives the
request and the downstream engine and returns the (transformed) response stream.
"""

from __future__ import annotations

import abc
from typing import AsyncIterator, Generic, TypeVar

from .engine import AsyncEngine, Context

InReq = TypeVar("InReq")
OutReq = TypeVar("OutReq")
InResp = TypeVar("InResp")
OutResp = TypeVar("OutResp")


class Operator(abc.ABC, Generic[InReq, OutReq, InResp, OutResp]):
    """A bidirectional pipeline stage.

    ``generate`` receives the incoming request and the *downstream* engine. A
    typical implementation transforms the request, iterates the downstream
    stream, and yields transformed items. Reference: `Operator`/`PipelineOperator`
    (lib/runtime/src/pipeline/nodes.rs), e.g. the OpenAI preprocessor operator
    (lib/llm/src/preprocessor.rs:64-359).
    """

    @abc.abstractmethod
    def generate(
        self, request: Context[InReq], next_engine: AsyncEngine[OutReq, InResp]
    ) -> AsyncIterator[OutResp]:
        ...


class _OperatorEngine(AsyncEngine[InReq, OutResp]):
    """Binds an operator to its downstream engine, forming a new engine."""

    def __init__(self, op: Operator, next_engine: AsyncEngine):
        self._op = op
        self._next = next_engine

    def generate(self, request: Context[InReq]) -> AsyncIterator[OutResp]:
        return self._op.generate(request, self._next)

    def __repr__(self) -> str:  # pragma: no cover
        return f"{type(self._op).__name__}→{self._next!r}"


class PipelineBuilder(Generic[InReq]):
    """Fluent `.link()` builder, mirroring the reference's segment linking.

    Usage::

        engine = (
            Pipeline()
            .link(OpenAIPreprocessorOperator(card))
            .link(DetokenizeOperator(card))
            .link_engine(jax_engine)
        )
    """

    def __init__(self) -> None:
        self._ops: list[Operator] = []

    def link(self, op: Operator) -> "PipelineBuilder":
        self._ops.append(op)
        return self

    def link_engine(self, engine: AsyncEngine) -> AsyncEngine:
        for op in reversed(self._ops):
            engine = _OperatorEngine(op, engine)
        return engine


def Pipeline() -> PipelineBuilder:
    return PipelineBuilder()


class MapOperator(Operator):
    """Stateless operator from two plain functions (request map, response map)."""

    def __init__(self, fwd=None, bwd=None):
        self._fwd = fwd or (lambda x: x)
        self._bwd = bwd or (lambda x: x)

    async def generate(self, request: Context, next_engine: AsyncEngine):
        downstream = request.map(self._fwd)
        async for item in next_engine.generate(downstream):
            yield self._bwd(item)
