"""Deterministic fault injection for the distributed planes.

Every dial in the runtime (statestore, message bus, RPC, KV transfer) goes
through :func:`open_connection` below. With no injector installed the
returned stream proxies cost one None-check per op; with one installed,
connects and per-frame reads/writes consult the injector's rule set and can

- **refuse**  — the dial raises ``ConnectionRefusedError`` (dead worker,
  statestore outage);
- **delay**   — the op completes after ``delay`` seconds (slow network,
  delayed watch events);
- **reset**   — the op raises ``ConnectionResetError`` (half-open
  connection, mid-stream worker death);
- **stall**   — the op blocks until :meth:`FaultInjector.release_stalls`
  (wedged worker; released stalls then surface as resets, like a half-open
  TCP connection finally dying).

Determinism: rule matching is positional (per-plane/addr op counters), and
any probabilistic rules draw from one seeded RNG — the same op sequence
under the same seed yields the same fault schedule. Tests assert recovery
behavior (failover, breaker trips, deadline expiry, re-registration)
without hand-rolled socket tricks, and chaos runs are replayable from the
seed alone.

Activation:

- programmatic: ``with faults.active(FaultInjector(rules, seed=42)): ...``
  (or ``install()``/``uninstall()`` for non-scoped use);
- environment:  ``DYN_TPU_FAULTS='[{"plane": "rpc", "action": "refuse"}]'``
  plus optional ``DYN_TPU_FAULT_SEED`` — parsed on first dial, so operator
  chaos drills need no code changes.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
import random
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple

from dynamo_tpu.runtime.envknobs import env_nonneg_int, env_raw

logger = logging.getLogger(__name__)

PLANES = ("statestore", "bus", "rpc", "transfer", "engine")
ACTIONS = ("refuse", "delay", "reset", "stall", "wedge", "cut", "blackout",
           "migrate_stall", "corrupt", "poison", "slow")
POINTS = ("connect", "read", "write", "serve", "item", "migrate", "pages",
          "dispatch")

# the decision log is bounded (PR8 decision-ring pattern): a soak run with
# a high-frequency rule fires millions of decisions — the replay log must
# stay a window, not a leak
FAULT_LOG_MAX = 256

# the planes a bare "blackout" kills: the whole control plane at once
# (discovery + events), leaving the RPC/transfer data planes alive — the
# docs/resilience.md §Control-plane blackout drill
CONTROL_PLANES = ("statestore", "bus")


class _BoundedLog(deque):
    """Bounded decision log that still answers the list idioms chaos tests
    use in their failure messages (``log[-10:]``)."""

    def __getitem__(self, i):
        if isinstance(i, slice):
            return list(self)[i]
        return deque.__getitem__(self, i)


class StreamCut(ConnectionResetError):
    """An injected mid-stream kill (action="cut" at point="item"): the
    serving side aborts the whole connection after the Nth response item —
    the deterministic stand-in for a worker process dying mid-decode. The
    client observes a connection reset with tokens already delivered,
    which is exactly the situation mid-stream resume must absorb."""


@dataclass
class FaultRule:
    """One fault to inject. Matching is AND across the fields:

    ``plane``       which transport ("statestore" | "bus" | "rpc" |
                    "transfer" | "*").
    ``point``       where it fires: "connect" (per dial), "read"/"write"
                    (per frame on an established connection), "serve"
                    (server-side dispatch gate, once per request/probe —
                    see :func:`serve_gate`), "item" (server-side, once per
                    streamed response item — ``after_ops`` counts items
                    WITHIN each stream, so "kill after the 3rd token" is
                    one rule; see :func:`item_gate`).
    ``action``      refuse | delay | reset | stall | wedge | cut (refuse
                    only makes sense at connect; wedge only at serve; cut
                    only at item — it aborts the serving connection, a
                    deterministic mid-decode worker death;
                    reset/delay/stall anywhere).
    ``match_addr``  exact "host:port" (None = any address).
    ``after_ops``   skip the first N matching ops (per plane+addr counter
                    for connects, per connection for reads/writes).
    ``max_fires``   total firings across the injector (None = unlimited).
    ``probability`` chance to fire when otherwise matching; draws from the
                    injector's seeded RNG (1.0 = always, deterministic).
    ``delay``       seconds, for action="delay" — and the FIXED part of a
                    "slow" dispatch delay (docs/resilience.md §Fail-slow:
                    the fail-slow drill injects ``delay + U[0, jitter)``
                    seconds at the engine dispatch point, per-plane
                    addressable like ``corrupt`` so one worker in a fleet
                    runs slow while the rest stay crisp).
    ``jitter``      seconds, for action="slow": uniform random extra delay
                    drawn from the injector's seeded RNG (replayable).
    """

    plane: str = "*"
    point: str = "connect"
    action: str = "refuse"
    match_addr: Optional[str] = None
    after_ops: int = 0
    max_fires: Optional[int] = None
    probability: float = 1.0
    delay: float = 0.0
    jitter: float = 0.0
    fired: int = field(default=0, compare=False)

    def matches(self, plane: str, addr: str, point: str, op_index: int) -> bool:
        if self.point != point:
            return False
        if self.plane != "*" and self.plane != plane:
            return False
        if self.match_addr is not None and self.match_addr != addr:
            return False
        if op_index < self.after_ops:
            return False
        if self.max_fires is not None and self.fired >= self.max_fires:
            return False
        return True

    @classmethod
    def from_dict(cls, d: dict) -> "FaultRule":
        known = {k: d[k] for k in (
            "plane", "point", "action", "match_addr", "after_ops",
            "max_fires", "probability", "delay", "jitter",
        ) if k in d}
        return cls(**known)


@dataclass
class FaultDecision:
    plane: str
    addr: str
    point: str
    op_index: int
    action: str
    # global draw-order stamp: two same-seed runs produce identical
    # (seq, ..., detail) logs, which is what makes --replay auditable
    seq: int = 0
    # the RNG draw the action consumed (corrupt's byte offset, slow's
    # jitter), recorded via FaultInjector.note_draw
    detail: str = ""


class FaultInjector:
    """Holds the rule set, the seeded RNG, and the decision log.

    The decision log records every fired fault in order — a chaos test that
    fails can print it (plus the seed) so the exact schedule is replayable.
    """

    def __init__(self, rules: Optional[List[FaultRule]] = None, seed: int = 0):
        self.rules: List[FaultRule] = list(rules or [])
        self.seed = seed
        self.rng = random.Random(seed)
        # bounded: one entry per FIRED decision, forever, was a leak under
        # soak-length runs with per-frame rules; the newest FAULT_LOG_MAX
        # decisions are plenty to replay a failure (plus the seed)
        self.log: "deque[FaultDecision]" = _BoundedLog(maxlen=FAULT_LOG_MAX)
        self._seq = 0
        self._connect_ops: Dict[Tuple[str, str], int] = {}
        self._serve_ops: Dict[Tuple[str, str], int] = {}
        self._sync_ops: Dict[Tuple[str, str, str], int] = {}
        self._stall_release = asyncio.Event()
        self._wedge_release = asyncio.Event()
        # blackout machinery: the refuse/reset rules currently simulating a
        # dead plane, plus strong refs to timed-end tasks (asyncio only
        # weakly references tasks)
        self._blackout_rules: List[FaultRule] = []
        self._blackout_tasks: set = set()

    def add_rule(self, rule: FaultRule) -> FaultRule:
        self.rules.append(rule)
        return rule

    def remove_rule(self, rule: FaultRule) -> None:
        with contextlib.suppress(ValueError):
            self.rules.remove(rule)

    def clear_rules(self) -> None:
        self.rules.clear()
        self._blackout_rules.clear()
        self.release_stalls()
        self.release_wedges()

    # -- blackout: kill whole planes for a while ---------------------------

    def begin_blackout(self, planes: Tuple[str, ...] = CONTROL_PLANES) -> None:
        """Simulate the named planes dying RIGHT NOW: new dials are refused
        and every live connection's next read/write resets — exactly what a
        crashed statestore/bus looks like from a client. Idempotent per
        plane; :meth:`end_blackout` restores service (clients then
        reconnect through their own recovery loops)."""
        active = {r.plane for r in self._blackout_rules}
        for plane in planes:
            if plane in active:
                continue
            fresh = [
                FaultRule(plane=plane, point="connect", action="refuse"),
                FaultRule(plane=plane, point="read", action="reset"),
                FaultRule(plane=plane, point="write", action="reset"),
            ]
            self._blackout_rules.extend(fresh)
            # front of the list so a blackout wins over any later rule
            self.rules[:0] = fresh

    def end_blackout(self, planes: Optional[Tuple[str, ...]] = None) -> None:
        """Lift the blackout for ``planes`` (default: all blacked out)."""
        ending = [
            r for r in self._blackout_rules
            if planes is None or r.plane in planes
        ]
        for r in ending:
            self.remove_rule(r)
            self._blackout_rules.remove(r)

    def blackout_active(self, plane: str) -> bool:
        return any(r.plane == plane for r in self._blackout_rules)

    async def blackout(
        self,
        duration: float,
        planes: Tuple[str, ...] = CONTROL_PLANES,
    ) -> None:
        """Scripted drill: black out ``planes``, hold for ``duration``
        seconds, restore."""
        self.begin_blackout(planes)
        try:
            await asyncio.sleep(duration)
        finally:
            self.end_blackout(planes)

    def _schedule_blackout_end(self, planes: Tuple[str, ...],
                               duration: float) -> None:
        async def _end() -> None:
            await asyncio.sleep(duration)
            self.end_blackout(planes)

        task = asyncio.get_running_loop().create_task(_end())
        self._blackout_tasks.add(task)
        task.add_done_callback(self._blackout_tasks.discard)

    def release_stalls(self) -> None:
        """Wake every stalled op; each then raises ConnectionResetError
        (a wedged connection that finally dies, not one that recovers)."""
        self._stall_release.set()
        self._stall_release = asyncio.Event()

    def release_wedges(self) -> None:
        """Wake every wedged serve gate; each request then PROCEEDS (an
        engine that un-sticks, unlike a stall's final death) — the
        self-healing half of a zombie-worker scenario. A wedge rule still
        installed re-wedges subsequent requests."""
        self._wedge_release.set()
        self._wedge_release = asyncio.Event()

    # -- decision core -----------------------------------------------------

    def decide(self, plane: str, addr: str, point: str, op_index: int
               ) -> Optional[FaultRule]:
        for rule in self.rules:
            if not rule.matches(plane, addr, point, op_index):
                continue
            if rule.probability < 1.0 and self.rng.random() >= rule.probability:
                continue
            rule.fired += 1
            self._seq += 1
            self.log.append(FaultDecision(
                plane, addr, point, op_index, rule.action, seq=self._seq,
            ))
            return rule
        return None

    def note_draw(self, detail: str) -> None:
        """Annotate the NEWEST logged decision with the RNG draw its action
        consumed (corrupt's byte offset, slow's jitter). Every seeded draw
        an action makes lands in the decision log in draw order, so two
        same-seed runs can be diffed entry-for-entry and a divergence
        points at the exact first nondeterministic draw."""
        if self.log:
            self.log[-1].detail = detail

    async def _apply(self, rule: FaultRule, what: str) -> None:
        if rule.action == "delay":
            await asyncio.sleep(rule.delay)
            return
        if rule.action == "reset":
            raise ConnectionResetError(f"injected reset ({what})")
        if rule.action == "stall":
            release = self._stall_release
            await release.wait()
            raise ConnectionResetError(f"injected stall released ({what})")
        if rule.action == "wedge":
            # zombie worker: the request parks here forever (connection
            # accepted, stream silent). On release it proceeds normally.
            release = self._wedge_release
            await release.wait()
            return
        if rule.action == "refuse":
            raise ConnectionRefusedError(f"injected refusal ({what})")
        if rule.action == "cut":
            raise StreamCut(f"injected mid-stream cut ({what})")
        if rule.action == "migrate_stall":
            # drain-migration chaos (docs/resilience.md §Live migration):
            # the coordinator's per-stream transfer parks here until
            # release_stalls — its migrate timeout then fires and the
            # stream degrades to the resume path. Released stalls die as
            # resets, like a transfer conn finally timing out.
            release = self._stall_release
            await release.wait()
            raise ConnectionResetError(
                f"injected migrate stall released ({what})"
            )
        if rule.action == "blackout":
            # env-driven control-plane blackout drill: the first matching op
            # starts a timed outage of the rule's plane ("*" = both control
            # planes) lasting `delay` seconds, and itself dies with a reset.
            # The trigger rule is spent NOW — without this, the clients' own
            # recovery redials after the timed end would re-match it and the
            # "30s blackout" drill would repeat forever
            rule.max_fires = rule.fired
            planes = (
                (rule.plane,) if rule.plane in PLANES else CONTROL_PLANES
            )
            self.begin_blackout(planes)
            if rule.delay > 0:
                self._schedule_blackout_end(planes, rule.delay)
            raise ConnectionResetError(f"injected blackout begins ({what})")
        raise ValueError(f"unknown fault action {rule.action!r}")

    # -- connection faulting ----------------------------------------------

    async def before_connect(self, plane: str, addr: str) -> None:
        key = (plane, addr)
        op = self._connect_ops.get(key, 0)
        self._connect_ops[key] = op + 1
        rule = self.decide(plane, addr, "connect", op)
        if rule is not None:
            await self._apply(rule, f"connect {plane} {addr}")

    async def before_serve(self, plane: str, addr: str) -> None:
        key = (plane, addr)
        op = self._serve_ops.get(key, 0)
        self._serve_ops[key] = op + 1
        rule = self.decide(plane, addr, "serve", op)
        if rule is not None:
            await self._apply(rule, f"serve {plane} {addr}")

    async def before_item(self, plane: str, addr: str, index: int) -> None:
        """Per-response-item gate: ``index`` is the item's position WITHIN
        its stream (passed by the server, not counted here), so
        ``after_ops=N`` reads "let N items through, then fire" for every
        matching stream — deterministic regardless of request interleaving.
        ``max_fires`` still bounds total firings across streams."""
        rule = self.decide(plane, addr, "item", index)
        if rule is not None:
            await self._apply(rule, f"item {plane} {addr} #{index}")

    def decide_sync(self, plane: str, addr: str, point: str,
                    action: str) -> bool:
        """Synchronous decision for data-mutating faults (``corrupt`` /
        ``poison``): returns True when a matching rule of exactly that
        action fired. Matching filters on the action BEFORE consuming the
        rule — a differently-actioned rule at the same point must neither
        burn its max_fires budget nor log a decision it never applied.
        Counted on a per-(plane, addr, point) op counter so ``after_ops``
        reads "let N page sets / dispatches through". Safe from any thread
        that owns its call site (the engine thread for ``dispatch``/
        host-tier ``pages``; the event loop for wire ``pages``) — rule
        bookkeeping is GIL-atomic appends/increments."""
        return self.decide_sync_rule(plane, addr, point, action) is not None

    def decide_sync_rule(self, plane: str, addr: str, point: str,
                         action: str) -> Optional[FaultRule]:
        """:meth:`decide_sync` returning the fired rule itself, for gates
        whose effect is parameterized by the rule (``slow`` reads its
        ``delay``/``jitter``)."""
        key = (plane, addr, point)
        op = self._sync_ops.get(key, 0)
        self._sync_ops[key] = op + 1
        for rule in self.rules:
            if rule.action != action:
                continue
            if not rule.matches(plane, addr, point, op):
                continue
            if (
                rule.probability < 1.0
                and self.rng.random() >= rule.probability
            ):
                continue
            rule.fired += 1
            self._seq += 1
            self.log.append(
                FaultDecision(plane, addr, point, op, rule.action,
                              seq=self._seq)
            )
            return rule
        return None

    async def before_migrate(self, plane: str, addr: str) -> None:
        """Per-migration gate (drain coordinator, once per stream shipped):
        ``addr`` is the TARGET's transfer address, so a rule can fault
        migrations toward one sibling while others succeed. Counted on the
        serve-op counter (per plane+addr)."""
        key = (plane, addr)
        op = self._serve_ops.get(key, 0)
        self._serve_ops[key] = op + 1
        rule = self.decide(plane, addr, "migrate", op)
        if rule is not None:
            await self._apply(rule, f"migrate {plane} {addr}")


class _ConnFaults:
    """Per-connection read/write op counters + rule application.

    Consults the *currently installed* injector on every op — not the one
    (if any) active at dial time — so an injector installed mid-run can
    break live connections, exactly like a real outage would. With no
    injector installed this is a None-check fast path.
    """

    __slots__ = ("plane", "addr", "reads", "writes", "broken")

    def __init__(self, plane: str, addr: str):
        self.plane = plane
        self.addr = addr
        self.reads = 0
        self.writes = 0
        self.broken = False

    def check_broken(self) -> None:
        if self.broken:
            raise ConnectionResetError(
                f"injected: connection already broken ({self.plane} {self.addr})"
            )

    async def before(self, point: str) -> None:
        injector = _active
        if injector is None:  # callers pre-check, but keep this guard too
            return
        self.check_broken()
        op = self.reads if point == "read" else self.writes
        if point == "read":
            self.reads += 1
        else:
            self.writes += 1
        rule = injector.decide(self.plane, self.addr, point, op)
        if rule is not None:
            try:
                await injector._apply(
                    rule, f"{point} {self.plane} {self.addr}"
                )
            except ConnectionError:
                self.broken = True
                raise


class _FaultyReader:
    """StreamReader proxy consulting the injector on every read call. The
    framed codec issues up to three reads per frame (prelude, header,
    body), so ``after_ops`` on read rules counts read *calls*, not frames —
    deterministic either way, since the call sequence is fixed per frame."""

    def __init__(self, inner: asyncio.StreamReader, state: _ConnFaults):
        self._inner = inner
        self._state = state

    async def readexactly(self, n: int) -> bytes:
        # None-check inline, not inside before(): the inactive fast path
        # must not even allocate the before() coroutine per frame read
        if _active is not None:
            await self._state.before("read")
        return await self._inner.readexactly(n)

    async def read(self, n: int = -1) -> bytes:
        if _active is not None:
            await self._state.before("read")
        return await self._inner.read(n)

    async def readline(self) -> bytes:
        if _active is not None:
            await self._state.before("read")
        return await self._inner.readline()

    def at_eof(self) -> bool:
        return self._inner.at_eof()


class _FaultyWriter:
    """StreamWriter proxy; write faults fire in drain() (every frame write
    in this codebase is a write()+drain() pair)."""

    def __init__(self, inner: asyncio.StreamWriter, state: _ConnFaults):
        self._inner = inner
        self._state = state

    def write(self, data: bytes) -> None:
        if _active is not None:
            # a broken connection swallows nothing: fail the write itself
            self._state.check_broken()
        self._inner.write(data)

    async def drain(self) -> None:
        if _active is not None:
            await self._state.before("write")
        await self._inner.drain()

    def close(self) -> None:
        self._inner.close()

    def is_closing(self) -> bool:
        return self._inner.is_closing()

    async def wait_closed(self) -> None:
        await self._inner.wait_closed()

    def get_extra_info(self, name: str, default=None):
        return self._inner.get_extra_info(name, default)


# =========================================================================
# activation
# =========================================================================

_active: Optional[FaultInjector] = None
_env_checked = False


def install(injector: FaultInjector) -> None:
    global _active
    _active = injector


def uninstall() -> None:
    global _active
    if _active is not None:
        _active.release_stalls()
        _active.release_wedges()
        _active.end_blackout()
    _active = None


@contextlib.contextmanager
def active(injector: FaultInjector) -> Iterator[FaultInjector]:
    """Scope an injector over a block; always uninstalled on exit."""
    install(injector)
    try:
        yield injector
    finally:
        uninstall()


def current() -> Optional[FaultInjector]:
    """The active injector, if any; checks the environment once."""
    global _active, _env_checked
    if _active is None and not _env_checked:
        _env_checked = True
        spec = env_raw("DYN_TPU_FAULTS")
        if spec:
            try:
                _active = injector_from_spec(
                    spec, seed=env_nonneg_int("DYN_TPU_FAULT_SEED", 0)
                )
                logger.warning(
                    "fault injection ACTIVE from DYN_TPU_FAULTS (%d rules, seed=%d)",
                    len(_active.rules), _active.seed,
                )
            except (ValueError, TypeError):
                logger.exception("malformed DYN_TPU_FAULTS spec ignored")
    return _active


def injector_from_spec(spec: str, seed: int = 0) -> FaultInjector:
    """Parse a JSON list of rule dicts into an injector."""
    raw = json.loads(spec)
    if not isinstance(raw, list):
        raise ValueError("DYN_TPU_FAULTS must be a JSON list of rule objects")
    return FaultInjector([FaultRule.from_dict(d) for d in raw], seed=seed)


async def serve_gate(plane: str, addr: str) -> None:
    """Server-side dispatch gate, consulted once per request/probe before
    the engine sees it (runtime/rpc.py ``_serve_request`` and ``__ping__``).

    ``addr`` is the serving side's own listen address, so a ``serve`` rule
    with ``match_addr`` targets one worker in a cluster. The ``wedge``
    action makes that worker a deterministic zombie: connections accepted,
    requests and pings parked forever — the health plane (probe timeouts,
    stall detection) must route around it. No injector ⇒ one None-check.
    """
    inj = current()
    if inj is not None:
        await inj.before_serve(plane, addr)


async def migrate_gate(plane: str, addr: str) -> None:
    """Drain-migration gate (disagg/migration.py), consulted once per
    stream before its pages ship to ``addr``. The ``migrate_stall`` action
    parks the transfer until :meth:`FaultInjector.release_stalls` — the
    coordinator's migrate timeout then degrades that stream to the resume
    path, which is exactly the chaos scenario the fallback tests drive.
    No injector ⇒ one None-check."""
    inj = current()
    if inj is not None:
        await inj.before_migrate(plane, addr)


async def item_gate(plane: str, addr: str, index: int) -> None:
    """Server-side per-response-item gate (runtime/rpc.py item loop).

    The ``cut`` action raises :class:`StreamCut`; the server aborts the
    whole connection — every stream on it dies exactly as if the worker
    process was killed after this stream's Nth item. The hot path pays one
    None-check per item when no injector is installed (callers pre-check
    :func:`current`)."""
    inj = current()
    if inj is not None:
        await inj.before_item(plane, addr, index)


def corrupt_pages(plane: str, addr: str, body: bytes) -> bytes:
    """Silent-corruption drill (docs/resilience.md §Silent corruption): the
    ``corrupt`` action at point ``pages`` bit-flips one byte of a packed
    KV page body at an offset drawn from the injector's seeded RNG (and
    recorded in the decision log via :meth:`FaultInjector.note_draw`), so
    a replayed schedule corrupts the same byte of the same block — and the
    flip lands anywhere in the page, not always mid-body, which is what
    real SDC looks like. Applied AFTER the sender
    computed its content checksums, which is exactly the post-seal SDC the
    checksum plane exists to catch; the receiver's verify turns the flip
    into a typed :class:`~dynamo_tpu.runtime.integrity.KvIntegrityError`
    instead of corrupt pool pages. No injector ⇒ the caller pre-checks
    :func:`current` (one None-check)."""
    inj = current()
    if inj is None or not body:
        return body
    if not inj.decide_sync(plane, addr, "pages", "corrupt"):
        return body
    i = inj.rng.randrange(len(body))
    inj.note_draw(f"offset={i}")
    return body[:i] + bytes([body[i] ^ 0x01]) + body[i + 1:]


def corrupt_array(plane: str, addr: str, arr):
    """Host-tier form of :func:`corrupt_pages`: bit-flips one byte of a
    numpy page array (the host KV pool's copy of an evicted block) — the
    "bad host RAM" leg of the silent-corruption drill. The byte offset is
    drawn from the injector's seeded RNG and recorded in the decision log,
    same replay contract as the wire form. Returns the (copied) corrupted
    array when the rule fires, the original otherwise."""
    inj = current()
    if inj is None:
        return arr
    if not inj.decide_sync(plane, addr, "pages", "corrupt"):
        return arr
    import numpy as np

    out = np.array(arr)  # device_get views may be read-only
    flat = out.view(np.uint8).reshape(-1)
    if flat.size == 0:
        return arr
    i = inj.rng.randrange(flat.size)
    inj.note_draw(f"offset={i}")
    flat[i] ^= 0x01
    return out


def slow_gate(plane: str, addr: str) -> float:
    """Engine-dispatch gate for the ``slow`` action at point ``dispatch``
    (docs/resilience.md §Fail-slow): seconds of injected host-side delay
    for THIS dispatch — ``rule.delay`` plus a uniform draw from
    ``[0, rule.jitter)`` off the injector's seeded RNG, so a replayed
    schedule slows the same dispatches by the same amounts. 0.0 when no
    rule fires. Models the gray failures the straggler plane exists for
    (thermal throttle, sick NIC, noisy co-tenant): the worker stays
    healthy by every existing probe, it is just *slow*. Synchronous,
    called from the engine thread once per dispatch; callers pre-check
    :func:`current` so the uninstrumented path pays one None-check."""
    inj = current()
    if inj is None:
        return 0.0
    rule = inj.decide_sync_rule(plane, addr, "dispatch", "slow")
    if rule is None:
        return 0.0
    d = max(rule.delay, 0.0)
    if rule.jitter > 0.0:
        j = rule.jitter * inj.rng.random()
        inj.note_draw(f"jitter={j:.6f}")
        d += j
    return d


def poison_gate(plane: str, addr: str) -> bool:
    """Engine-dispatch gate for the ``poison`` action at point
    ``dispatch``: True ⇒ this dispatch's logits are overwritten with NaN
    in-jit (the engine's watchdog input), modelling a core that computes
    garbage — the output watchdog must catch the lane before any token
    reaches a client. Synchronous: called from the engine thread once per
    dispatch, one None-check when no injector is installed."""
    inj = current()
    if inj is None:
        return False
    return inj.decide_sync(plane, addr, "dispatch", "poison")


async def open_connection(host: str, port: int, plane: str = "rpc"):
    """Dial ``host:port``, subject to the active injector (if any).

    Every runtime transport dials through here so one harness can fault any
    plane. The returned streams are always wrapped (a None-check per op when
    no injector is installed) so that an injector installed *later* can
    break connections that are already live — a real outage doesn't spare
    established sockets.
    """
    inj = current()
    if inj is not None:
        await inj.before_connect(plane, f"{host}:{port}")
    reader, writer = await asyncio.open_connection(host, port)
    state = _ConnFaults(plane, f"{host}:{port}")
    return _FaultyReader(reader, state), _FaultyWriter(writer, state)
