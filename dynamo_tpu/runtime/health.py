"""Worker health plane: proactive liveness, stall detection, stuck-request
reaping, and self-healing.

PR 2/3 made the request path *react* well to failure (failover, breakers,
overload sheds, drain mode) — but every one of those mechanisms fires only
after a user request has already paid for the discovery. A **zombie worker**
(registered in the statestore, accepting TCP, engine thread wedged) keeps
attracting traffic until each routed request burns its full deadline. This
module is the *proactive* half of fault tolerance:

- :class:`HealthPolicy` — the knob bundle, env-tunable via ``DYN_TPU_HEALTH_*``
  with the same clamping contract as the admission parsers (malformed / zero /
  negative → defaults).
- :class:`EngineHeartbeat` — a monotonic progress counter the ``engine_jax``
  step loop bumps every iteration. No beat while the engine is busy for
  longer than ``stall_timeout`` ⇒ the engine thread is wedged.
- :class:`HealthMonitor` — the per-worker self-check loop: engine-heartbeat
  stall detection, an asyncio event-loop lag probe, sub-engine health
  aggregation (e.g. a crash-looping subprocess engine), and the
  **stuck-request reaper** (``RpcServer.reap_expired``) that aborts requests
  past ``deadline + reap_grace``, returning their slots and KV blocks to the
  engine and emitting a terminal error item. An ``unhealthy`` worker
  self-drains through PR 3's drain machinery (source ``"health"``) and
  re-admits itself after ``recovery_checks`` consecutive passing checks.

Health states ride the existing planes: the load-report heartbeat re-puts
the instance key with ``health`` (+ stall/reap counters), RPC replies
piggyback it in the ``load`` snapshot, and ``EndpointClient`` actively
probes silent instances with the ``__ping__`` RPC verb (runtime/rpc.py) —
which round-trips through the real dispatch path, so a wedged worker times
the probe out instead of answering from a healthy socket.

States: ``healthy`` (full service) → ``degraded`` (observably impaired —
event-loop lag — but still serving) → ``unhealthy`` (self-drained, routed
around). See docs/health.md for the runbook.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import time
import weakref
from dataclasses import dataclass
from typing import Callable, Optional

from dynamo_tpu.runtime.admission import _env_pos_float, _env_pos_int

logger = logging.getLogger(__name__)

# Health states (plain strings: they cross the wire in load snapshots and
# instance keys, and read well in logs/metrics).
HEALTHY = "healthy"
DEGRADED = "degraded"
UNHEALTHY = "unhealthy"
# quarantined (docs/resilience.md §Silent corruption): the integrity plane
# latched — this worker's outputs/pages are untrusted. Routers exclude it
# like unhealthy, it self-drains, and UNLIKE unhealthy it never recovers by
# passing checks: only `llmctl worker unquarantine` (or the trips source
# clearing) re-admits it, because a host that silently corrupts data does
# not become trustworthy by being briefly quiet.
QUARANTINED = "quarantined"
# suspect (docs/resilience.md §Fail-slow): the telemetry aggregator judged
# this worker slow relative to its live peers (runtime/straggler.py). A
# SOFT state between healthy and unhealthy: the worker still serves —
# clients soft-demote it to route-of-last-resort instead of excluding it
# (an all-slow fleet must keep serving), its KV stays trusted (unlike
# quarantine, so inflight streams migrate off with their pages), and it
# recovers on its own when the aggregator clears the verdict.
SUSPECT = "suspect"
STATES = (HEALTHY, DEGRADED, SUSPECT, UNHEALTHY, QUARANTINED)

# drain source the monitor uses with DistributedRuntime.set_draining — kept
# distinct from "local" (SIGUSR1) and "store" (llmctl) so a self-heal never
# cancels an operator's drain and vice versa
DRAIN_SOURCE = "health"
# quarantine uses its OWN drain source: an unquarantine must not cancel a
# health/operator drain, and a health recovery must not undo a quarantine
QUARANTINE_SOURCE = "quarantine"
# the straggler plane's drain pulse (distributed.py _straggler_control_loop
# migrates a CONFIRMED straggler's inflight streams off) also keeps its own
# source: a straggler recovery must not cancel a health/operator/quarantine
# drain, and vice versa
STRAGGLER_SOURCE = "straggler"


@dataclass
class HealthPolicy:
    """Per-worker health knobs (``HealthPolicy.from_env()``).

    ``stall_timeout``       seconds the engine heartbeat may go silent while
                            the engine is busy before the worker is stalled
                            (``DYN_TPU_HEALTH_STALL_S``).
    ``check_interval``      self-check cadence (``DYN_TPU_HEALTH_CHECK_INTERVAL``).
    ``loop_lag_threshold``  event-loop lag above this marks the worker
                            degraded (``DYN_TPU_HEALTH_LOOP_LAG_S``).
    ``reap_grace``          how far past its deadline a stuck request may
                            linger before the reaper aborts it
                            (``DYN_TPU_HEALTH_REAP_GRACE_S``).
    ``probe_idle``          clients ping an instance that produced no RPC
                            traffic for this long (``DYN_TPU_HEALTH_PROBE_IDLE_S``).
    ``probe_timeout``       per-ping bound (``DYN_TPU_HEALTH_PROBE_TIMEOUT_S``).
    ``recovery_checks``     consecutive passing checks before an unhealthy
                            worker re-admits itself
                            (``DYN_TPU_HEALTH_RECOVERY_CHECKS``).
    """

    stall_timeout: float = 10.0
    check_interval: float = 1.0
    loop_lag_threshold: float = 1.0
    reap_grace: float = 5.0
    probe_idle: float = 10.0
    probe_timeout: float = 2.0
    recovery_checks: int = 3

    @classmethod
    def from_env(cls, prefix: str = "DYN_TPU_HEALTH_") -> "HealthPolicy":
        d = cls()
        return cls(
            stall_timeout=_env_pos_float(prefix + "STALL_S", d.stall_timeout),
            check_interval=_env_pos_float(
                prefix + "CHECK_INTERVAL", d.check_interval
            ),
            loop_lag_threshold=_env_pos_float(
                prefix + "LOOP_LAG_S", d.loop_lag_threshold
            ),
            reap_grace=_env_pos_float(prefix + "REAP_GRACE_S", d.reap_grace),
            probe_idle=_env_pos_float(prefix + "PROBE_IDLE_S", d.probe_idle),
            probe_timeout=_env_pos_float(
                prefix + "PROBE_TIMEOUT_S", d.probe_timeout
            ),
            recovery_checks=_env_pos_int(
                prefix + "RECOVERY_CHECKS", d.recovery_checks
            ),
        )


class EngineHeartbeat:
    """Monotonic progress signal bumped by the engine's step loop.

    ``beat(busy=...)`` is called once per loop iteration from the engine
    thread; the monitor reads ``age()``/``busy`` from the asyncio thread.
    Single-word reads/writes only (GIL-atomic) — deliberately no lock, so a
    wedged engine thread can never wedge the monitor through it. ``busy``
    records whether the engine had work at the LAST beat: an idle engine
    parks in its condition wait (no beats, busy False — not a stall); a
    busy one that stops beating is exactly the zombie signature.
    """

    __slots__ = ("_last", "_busy", "beats")

    def __init__(self) -> None:
        self._last = time.monotonic()
        self._busy = False
        self.beats = 0

    def beat(self, busy: bool) -> None:
        self._busy = bool(busy)
        self.beats += 1
        # written last: a reader seeing the fresh timestamp sees fresh state
        self._last = time.monotonic()

    @property
    def busy(self) -> bool:
        return self._busy

    def age(self) -> float:
        return time.monotonic() - self._last


# every constructed monitor, for the test-suite leak guard (conftest fails a
# test that leaves a started monitor running past teardown)
_MONITORS: "weakref.WeakSet[HealthMonitor]" = weakref.WeakSet()


def live_monitors() -> list:
    """Monitors whose check task is still running (leak-guard hook)."""
    return [m for m in _MONITORS if m._task is not None and not m._task.done()]


class HealthMonitor:
    """Per-worker self-check loop + health state machine.

    ``server`` is duck-typed (an :class:`~dynamo_tpu.runtime.rpc.RpcServer`):
    it provides ``engines()`` for the heartbeat/sub-engine sweep and
    ``reap_expired()`` for the stuck-request reaper. ``set_draining(flag,
    source=...)`` is the runtime hook the unhealthy⇄healthy transitions
    drive (PR 3 drain machinery; absent in bare-server tests).
    """

    def __init__(
        self,
        policy: Optional[HealthPolicy] = None,
        server=None,
        set_draining: Optional[Callable] = None,
    ):
        self.policy = policy or HealthPolicy.from_env()
        self.server = server
        self.set_draining = set_draining
        self.state = HEALTHY
        # counters published on the metrics plane + instance-key heartbeats
        # (reaped_requests_total is a property over the server's counter —
        # one source of truth, whoever drives reap_expired)
        self.stalls_total = 0
        self.checks_total = 0
        self.loop_lag = 0.0
        self.loop_lag_max = 0.0
        self._stalled = False
        self._healthy_streak = 0
        self._task: Optional[asyncio.Task] = None
        _MONITORS.add(self)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._task
            self._task = None

    @property
    def reaped_requests_total(self) -> int:
        """The server's reap counter (single source of truth — tests and
        manual sweeps call ``reap_expired`` too, and two counters would
        silently diverge)."""
        return getattr(self.server, "reaped_total", 0) or 0

    def counters(self) -> dict:
        return {
            "stalls_total": self.stalls_total,
            "reaped_requests_total": self.reaped_requests_total,
        }

    # -- check loop --------------------------------------------------------

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        interval = self.policy.check_interval
        while True:
            t0 = loop.time()
            await asyncio.sleep(interval)
            # the sleep doubles as the event-loop lag probe: oversleep means
            # something (a blocking call, a starved loop) held the thread
            lag = max(loop.time() - t0 - interval, 0.0)
            try:
                self.check(lag)
                if self.server is not None:
                    await self.server.reap_expired(self.policy.reap_grace)
            except asyncio.CancelledError:
                raise
            except Exception:
                # a broken check must degrade to "no health plane", never
                # take the worker down with it
                logger.exception("health check failed")

    def check(self, lag: float = 0.0) -> str:
        """Run one self-check pass (sync; the loop calls it, tests may too).
        Returns the resulting state."""
        self.checks_total += 1
        self.loop_lag = lag
        self.loop_lag_max = max(self.loop_lag_max, lag)
        stalled = False
        sub_unhealthy = False
        engines = self.server.engines() if self.server is not None else ()
        for eng in engines:
            hb = getattr(eng, "heartbeat", None)
            if (
                hb is not None
                and hb.busy
                and hb.age() > self.policy.stall_timeout
            ):
                stalled = True
            # sub-engine self-reports (e.g. a subprocess engine that gave up
            # its crash-loop) bubble up to the worker state
            if getattr(eng, "health_state", HEALTHY) == UNHEALTHY:
                sub_unhealthy = True
        if stalled and not self._stalled:
            self.stalls_total += 1
            logger.error(
                "engine stall detected: busy with no step-loop progress for "
                "> %.1fs", self.policy.stall_timeout,
            )
        self._stalled = stalled
        # the quarantine latch (runtime/integrity.py) outranks everything:
        # a worker producing corrupt KV/logits must not look merely
        # "degraded" — and must not recover by passing ordinary checks.
        # Constructor-free read: one module-global check per tick.
        from dynamo_tpu.runtime import integrity

        # the straggler verdict latch (runtime/straggler.py) sits BETWEEN
        # unhealthy and degraded: fleet-relative slowness is softer than a
        # wedged engine (the worker still serves, last-resort) but graver
        # than local loop lag. Constructor-free module-global read, same
        # zero-overhead contract as the quarantine latch.
        from dynamo_tpu.runtime import straggler

        if integrity.quarantined():
            candidate = QUARANTINED
        elif stalled or sub_unhealthy:
            candidate = UNHEALTHY
        elif straggler.verdict() != straggler.OK:
            candidate = SUSPECT
        elif lag > self.policy.loop_lag_threshold:
            candidate = DEGRADED
        else:
            candidate = HEALTHY
        self._transition(candidate)
        return self.state

    def _transition(self, new: str) -> None:
        # suspect needs no hysteresis of its own: the aggregator's window
        # machinery (runtime/straggler.py StragglerArbiter) already owns
        # the flap damping, so the worker mirrors the latched verdict
        # immediately both ways. It also does not self-drain here — the
        # straggler control loop (distributed.py) drives the migrate-off
        # drain pulse under its own source; soft-demotion in the clients
        # handles routing for plain suspects.
        if new == QUARANTINED or self.state == QUARANTINED:
            # no hysteresis either way: latching quarantine is immediate
            # (every check until the latch clears re-candidates it), and
            # LEAVING it is an operator decision already made — the
            # integrity tracker was explicitly cleared
            self._healthy_streak = 0
        elif self.state == UNHEALTHY and new != UNHEALTHY:
            # hysteresis: one good check must not flap an unhealthy worker
            # back into rotation — require a full recovery streak
            self._healthy_streak += 1
            if self._healthy_streak < self.policy.recovery_checks:
                return
        if new == UNHEALTHY:
            self._healthy_streak = 0
        if new == self.state:
            return
        old, self.state = self.state, new
        log = logger.warning if new != HEALTHY else logger.info
        log("worker health: %s -> %s", old, new)
        if self.set_draining is not None:
            if new == QUARANTINED:
                # quarantine self-drain: routers stop dispatching, and the
                # migration coordinator sees the latch and degrades the
                # drain to resume directives — untrusted pages never
                # replicate into healthy siblings
                self.set_draining(True, source=QUARANTINE_SOURCE)
            elif old == QUARANTINED:
                self.set_draining(False, source=QUARANTINE_SOURCE)
            if new == UNHEALTHY:
                # self-drain: routers stop dispatching here, in-flight
                # streams finish; the statestore registration stays (the
                # worker is sick, not gone)
                self.set_draining(True, source=DRAIN_SOURCE)
            elif old == UNHEALTHY:
                self.set_draining(False, source=DRAIN_SOURCE)
