"""Fail-slow defense: differential straggler detection (docs/resilience.md
§Fail-slow).

The operate-under-failure planes (PR10-13) catch workers that die, drain,
or lie — but a worker that is merely *slow* (thermal throttle, failing
NIC, one sick chip in a pod, noisy co-tenant) sails through every one of
those checks: it heartbeats, it answers ``__ping__``, its checksums
verify, and it silently drags every stream routed to it. That is the
classic gray-failure / fail-slow gap ("Gray Failure", HotOS'17;
"Fail-Slow at Scale", FAST'18), and its fix is *differential*
observability: judge each worker against its live peers, never against an
absolute threshold a heterogeneous fleet would trip on day one.

Three pieces live here, split by where they run:

- :class:`StragglerPolicy` — the ``DYN_TPU_STRAGGLER*`` knob bundle (PR3
  clamping contract). ``DYN_TPU_STRAGGLER`` defaults OFF and is THE
  zero-overhead gate: with it unset no detector is ever constructed (the
  test suite monkeypatches the constructor to prove it) and the engine
  step loop pays one attribute None-check per dispatch.
- :class:`StragglerDetector` — the *worker*-side half: a process-global,
  thread-safe EWMA of wall-microseconds-per-token over the engine's
  per-dispatch timings (ring-buffered for debug dumps). It produces the
  ``dispatch_us_per_token_ewma`` gauge that rides the ordinary metrics
  stream — the detector never judges; normalized latency means nothing
  without peers to compare against.
- :class:`StragglerArbiter` — the *aggregator*-side half: fleet-relative
  verdicts. Per model group, once per detection window, a worker whose
  EWMA exceeds ``factor ×`` the peer median (with ``min_peers`` fresh
  reporters) takes a window trip: one trip ⇒ ``suspect`` (soft-demoted,
  route of last resort), ``trips`` consecutive windows ⇒ ``confirmed``
  (migration donor — the drain pulse ships its inflight streams to
  faster siblings). A uniformly-loaded fleet produces ZERO false
  positives, and — unlike PR13's sticky quarantine — the verdict is
  recoverable: one full window back inside the peer envelope clears it.
  Workers with no fresh samples in a window HOLD their state (a drained/
  paused worker stops producing samples; it never produces slow ones —
  the drain-composition defense), except that a *demoted* worker starved
  of samples for several consecutive windows decays one severity level
  per probation period — soft-demotion is what starved it, so held
  verdicts must expire or a recovered worker could never prove itself.

Verdicts travel worker-ward over the existing control-key channel
(``{ns}/straggler/{worker_id}``, the quarantine-latch pattern): the
aggregator puts/deletes keys under ITS lease (a dead arbiter's verdicts
expire instead of wedging the fleet demoted), each worker's control loop
watches the prefix and latches the module-global verdict below, and the
health plane reports the new soft state ``suspect`` on every existing
wire path (load snapshots, instance keys, ``__ping__`` pongs) with zero
new plumbing. The latch is deliberately independent of the detector so a
drill (``llmctl``/tests writing the key by hand) works with the sampling
plane off.
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from dynamo_tpu.runtime.envknobs import (
    env_clamped_float as _env_clamped_float,
    env_clamped_int as _env_clamped_int,
    env_flag as _env_flag,
)

logger = logging.getLogger(__name__)

ENV_STRAGGLER = "DYN_TPU_STRAGGLER"
ENV_FACTOR = "DYN_TPU_STRAGGLER_FACTOR"
ENV_WINDOW = "DYN_TPU_STRAGGLER_WINDOW"
ENV_MIN_PEERS = "DYN_TPU_STRAGGLER_MIN_PEERS"
ENV_TRIPS = "DYN_TPU_STRAGGLER_TRIPS"

# verdict states, in severity order. Plain strings: they cross the wire in
# metrics snapshots and control keys, and read well in logs.
OK = "ok"
SUSPECT = "suspect"
CONFIRMED = "confirmed"
STATES = (OK, SUSPECT, CONFIRMED)

# store-key prefix segment for verdict distribution (the quarantine-latch
# channel shape: "{namespace}/straggler/{worker_id}")
CONTROL_PREFIX = "straggler"


@dataclass(frozen=True)
class StragglerPolicy:
    """Knob bundle for the fail-slow plane (PR3 clamping contract:
    malformed / non-positive values fall back to defaults, out-of-range
    values clamp into the documented bounds).

    ``enabled``    DYN_TPU_STRAGGLER (default OFF — 1 arms the plane;
                   0/unset is the zero-overhead gate: no detector, no
                   arbiter, no control loop is ever constructed).
    ``factor``     DYN_TPU_STRAGGLER_FACTOR: a worker is slow when its
                   per-token EWMA exceeds ``factor ×`` the peer median
                   (clamped to [1.1, 100] — at 1.0 ordinary jitter would
                   flag half the fleet).
    ``window``     DYN_TPU_STRAGGLER_WINDOW: detection window seconds
                   (clamped to [0.2, 3600]); verdicts advance/clear at
                   window boundaries only.
    ``min_peers``  DYN_TPU_STRAGGLER_MIN_PEERS: fresh reporters required
                   before any verdict (clamped to [2, 4096] — a fleet of
                   one has no peers, hence no differential signal).
    ``trips``      DYN_TPU_STRAGGLER_TRIPS: consecutive slow windows
                   before suspect escalates to confirmed (migration
                   donor; clamped to [1, 100]).
    """

    enabled: bool = False
    factor: float = 3.0
    window: float = 30.0
    min_peers: int = 2
    trips: int = 3

    @classmethod
    def from_env(cls) -> "StragglerPolicy":
        d = cls()
        return cls(
            enabled=_env_flag(ENV_STRAGGLER, d.enabled),
            factor=_env_clamped_float(ENV_FACTOR, d.factor, 1.1, 100.0),
            window=_env_clamped_float(ENV_WINDOW, d.window, 0.2, 3600.0),
            min_peers=_env_clamped_int(ENV_MIN_PEERS, d.min_peers, 2, 4096),
            trips=_env_clamped_int(ENV_TRIPS, d.trips, 1, 100),
        )


def maybe_from_env() -> Optional[StragglerPolicy]:
    """The gate every integration point None-checks: ``None`` unless the
    fail-slow plane is armed — with ``DYN_TPU_STRAGGLER`` unset/0 no
    policy object is ever constructed (the PR9/PR13/PR14 pattern)."""
    if not _env_flag(ENV_STRAGGLER, False):
        return None
    return StragglerPolicy.from_env()


def enabled() -> bool:
    """Cheap boolean form of the gate (one env read, no object)."""
    return _env_flag(ENV_STRAGGLER, False)


# ---------------------------------------------------------------------------
# worker side: the per-dispatch timing feed
# ---------------------------------------------------------------------------


class StragglerDetector:
    """Process-global EWMA of wall-us-per-token over engine dispatches.

    Constructed lazily behind :func:`maybe_detector` — with the plane off
    nothing ever constructs it (the zero-overhead guard monkeypatches this
    constructor to prove it). Thread-safe: the engine step thread feeds,
    the metrics/RPC threads read.

    Wall time (not device time) on purpose: the failure modes this plane
    exists for — thermal throttle, a failing NIC stretching host fetches,
    a noisy co-tenant stealing the host CPU — can land on either side of
    the device/host split, and a victim stream experiences their SUM. The
    per-phase EWMAs are kept for debug dumps; the published gauge is the
    all-phase blend, which is what peers are compared on.
    """

    # ring of recent (phase, us_per_token) samples for debug dumps — a
    # window, never a leak (the decision-log bound pattern)
    RING = 512
    # EWMA smoothing: ~weighting the last ~20 dispatches. Fast enough to
    # cross a detection window, slow enough that one hiccup dispatch
    # cannot impersonate a sick worker.
    ALPHA = 0.1

    def __init__(self, alpha: float = ALPHA):
        self._alpha = float(alpha)
        self._lock = threading.Lock()
        self._ewma = 0.0
        self._phase_ewma: Dict[str, float] = {}
        self._ring: deque = deque(maxlen=self.RING)
        self.samples_total = 0

    def note_dispatch(self, phase: str, wall_us: float, tokens: int) -> None:
        """One dispatch: ``wall_us`` of step-loop wall time advancing
        ``tokens`` tokens. Token-free dispatches (a cancelled-lane sweep)
        carry no per-token signal and are skipped."""
        if tokens <= 0 or wall_us < 0.0:
            return
        upt = wall_us / tokens
        with self._lock:
            self.samples_total += 1
            self._ewma = (
                upt if self.samples_total == 1
                else self._ewma + self._alpha * (upt - self._ewma)
            )
            prev = self._phase_ewma.get(phase)
            self._phase_ewma[phase] = (
                upt if prev is None else prev + self._alpha * (upt - prev)
            )
            self._ring.append((phase, round(upt, 1)))

    def us_per_token(self) -> float:
        with self._lock:
            return self._ewma

    def gauges(self) -> Dict[str, Any]:
        """The worker-gauge view (ForwardPassMetrics fields), merged into
        the engine's metrics snapshot: the normalized latency the arbiter
        compares across peers, plus the cumulative sample counter the
        arbiter uses for freshness (a stale EWMA from a paused worker must
        not be judged — see the drain-composition defense)."""
        with self._lock:
            return {
                "dispatch_us_per_token_ewma": round(self._ewma, 1),
                "straggler_samples_total": self.samples_total,
            }

    def debug_dump(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "us_per_token_ewma": round(self._ewma, 1),
                "samples_total": self.samples_total,
                "phase_ewma": {
                    k: round(v, 1) for k, v in self._phase_ewma.items()
                },
                "recent": list(self._ring)[-32:],
            }


# ---------------------------------------------------------------------------
# process-global accessors (constructor-free reads, lazy gated writes)
# ---------------------------------------------------------------------------

_DETECTOR: Optional[StragglerDetector] = None
_LOCK = threading.Lock()

# the worker's latched fleet-relative verdict, pushed back from the
# aggregator over the control-key channel. Module-global and independent
# of the detector ON PURPOSE: the health plane reads it constructor-free
# every check tick, and a drill that writes the control key by hand must
# work with the sampling plane off.
_VERDICT = OK


def maybe_detector() -> Optional[StragglerDetector]:
    """The engine's init-time hook: the process-global detector when the
    plane is armed, else ``None`` — nothing is ever constructed with
    ``DYN_TPU_STRAGGLER`` unset (the zero-overhead contract)."""
    global _DETECTOR
    if not enabled():
        return None
    if _DETECTOR is None:
        with _LOCK:
            if _DETECTOR is None:
                _DETECTOR = StragglerDetector()
    return _DETECTOR


def detector_gauges() -> Dict[str, Any]:
    """Constructor-free gauge read for the metrics publisher: empty dict
    until anything armed the plane in this process."""
    det = _DETECTOR
    if det is None:
        return {}
    return det.gauges()


def verdict() -> str:
    """The worker's current fleet-relative verdict ("ok" | "suspect" |
    "confirmed"). Constructor-free, one module-global read — the health
    monitor calls this every check tick with the plane off too."""
    return _VERDICT


def set_verdict(state: str) -> None:
    """Latch a verdict pushed from the aggregator (control-key loop) or a
    drill. Unknown states are dropped with a warning rather than raised —
    a newer aggregator must not crash an older worker's control loop."""
    global _VERDICT
    if state not in STATES:
        logger.warning("ignoring unknown straggler verdict %r", state)
        return
    if state != _VERDICT:
        log = logger.warning if state != OK else logger.info
        log("straggler verdict: %s -> %s", _VERDICT, state)
    _VERDICT = state


def clear_verdict() -> None:
    set_verdict(OK)


def reset_for_tests() -> None:
    """Drop the process-global detector and verdict latch (conftest
    autouse reset: one test's samples or latched verdict must not bleed
    into another's health checks)."""
    global _DETECTOR, _VERDICT
    with _LOCK:
        _DETECTOR = None
        _VERDICT = OK


# ---------------------------------------------------------------------------
# aggregator side: fleet-relative verdicts
# ---------------------------------------------------------------------------


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    if n == 0:
        return 0.0
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


class _WorkerRecord:
    __slots__ = (
        "model", "ewma", "samples", "samples_at_window", "state", "trips",
        "stale_windows", "last_seen",
    )

    def __init__(self, model: str):
        self.model = model
        self.ewma = 0.0
        self.samples = 0
        self.samples_at_window = 0
        self.state = OK
        self.trips = 0
        self.stale_windows = 0
        self.last_seen = 0.0


class StragglerArbiter:
    """Fleet-relative verdict engine (runs at the telemetry aggregator).

    Pure and clock-injected: callers pass ``now`` (any monotonic source)
    into :meth:`observe`/:meth:`evaluate`, so tests drive whole detection
    windows without sleeping. Per model group, at each window boundary:

    - **fresh** workers (≥1 new detector sample since the last boundary,
      nonzero EWMA) are judged; **stale** workers HOLD their state — a
      worker paused by a PR12 drain stops producing samples, and a pause
      is not slowness (the drain-composition regression).
    - with ≥ ``min_peers`` fresh reporters, a fresh worker whose EWMA
      exceeds ``factor × median(fresh EWMAs)`` takes a window trip:
      ``suspect`` at one, ``confirmed`` at ``trips`` consecutive.
    - a fresh worker back inside the envelope for the FULL window (i.e.
      judged clean at a boundary) clears straight to ``ok`` — recoverable
      by design, unlike the integrity quarantine: slowness has benign
      transient causes; corruption does not.

    The median is taken over *all* fresh workers including the suspect
    ones: with a majority-healthy fleet the median is a healthy worker,
    and on an all-slow fleet (thermal event hits the whole pod) nobody
    exceeds ``factor × median`` — the fleet stays un-demoted and keeps
    serving, which is the soft-demotion contract.

    **Probation decay** closes the starvation loop: soft-demotion routes
    traffic AWAY from a suspect, which starves it of dispatches, which
    means no fresh samples — and a plain HOLD would then pin the verdict
    forever with no way to prove recovery. So a *demoted* worker that
    stays stale (heartbeating, but zero fresh samples) for
    ``PROBATION_WINDOWS`` consecutive windows decays ONE severity level
    (confirmed → suspect → ok): the demotion is a lease on evidence, and
    starved of evidence it expires. If the worker is genuinely still
    slow, its first real window of traffic re-trips it (bounded
    oscillation: slow exposure is ~1 window in ``PROBATION_WINDOWS+1``);
    if it recovered, it rejoins silently. Decay only ever *removes*
    verdicts, so the drain-composition guarantee — a paused worker is
    never *judged* slow — is untouched.
    """

    # drop workers not heard from for this many windows (left fleet)
    EXPIRE_WINDOWS = 10.0
    # consecutive sample-free windows before a demoted worker's verdict
    # decays one severity level (the starvation-recovery probe cycle)
    PROBATION_WINDOWS = 8

    def __init__(self, policy: Optional[StragglerPolicy] = None):
        self.policy = policy or StragglerPolicy.from_env()
        self._workers: Dict[str, _WorkerRecord] = {}
        self._window_start: Optional[float] = None
        self.windows_total = 0
        self.trips_total = 0

    def observe(
        self, worker_id: str, model: str, ewma: float, samples_total: int,
        now: float,
    ) -> None:
        """One metrics-stream observation for ``worker_id``. Cheap and
        unconditional — judgment happens only at window boundaries."""
        rec = self._workers.get(worker_id)
        if rec is None:
            rec = self._workers[worker_id] = _WorkerRecord(model)
            # anchor a first-seen worker at its CURRENT counter: it is
            # judged only once it produces a sample after this point. A
            # worker that freezes right after first sight (drained, or
            # seen across an aggregator restart mid-drain) would otherwise
            # be judged on a stale queue-flush EWMA — the drain-pause
            # misattribution the freshness gate exists to prevent. Costs
            # newly-joined workers one extra window of detection latency;
            # steady-state detection is unaffected.
            rec.samples_at_window = max(int(samples_total), 0)
        rec.model = model or rec.model
        rec.ewma = float(ewma)
        rec.samples = int(samples_total)
        rec.last_seen = now
        if self._window_start is None:
            self._window_start = now

    def evaluate(self, now: float) -> Dict[str, str]:
        """Advance the verdict machine if a full window has elapsed.
        Returns only the CHANGED verdicts ``{worker_id: state}`` (the
        store-sync loop puts/deletes exactly these keys); ``{}`` when the
        window hasn't closed or nothing changed."""
        if self._window_start is None:
            return {}
        if now - self._window_start < self.policy.window:
            return {}
        self.windows_total += 1
        changed: Dict[str, str] = {}
        expire = self.policy.window * self.EXPIRE_WINDOWS
        by_model: Dict[str, List[str]] = {}
        for wid, rec in list(self._workers.items()):
            if now - rec.last_seen > expire:
                del self._workers[wid]
                if rec.state != OK:
                    changed[wid] = OK
                continue
            by_model.setdefault(rec.model, []).append(wid)
        for wids in by_model.values():
            fresh = [
                w for w in wids
                if self._workers[w].samples > self._workers[w].samples_at_window
                and self._workers[w].ewma > 0.0
            ]
            fresh_set = set(fresh)
            # probation decay runs BEFORE (and regardless of) the
            # min_peers gate: a starved suspect must be able to shed its
            # verdict even when the fleet shrank below judging quorum
            for w in wids:
                rec = self._workers[w]
                if w in fresh_set:
                    rec.stale_windows = 0
                    continue
                if rec.state == OK:
                    continue
                rec.stale_windows += 1
                if rec.stale_windows < self.PROBATION_WINDOWS:
                    continue
                rec.stale_windows = 0
                if rec.state == CONFIRMED:
                    new = SUSPECT
                    # one more slow window re-confirms: the probe cycle
                    # must not restart the whole trip ladder
                    rec.trips = max(self.policy.trips - 1, 0)
                else:
                    new = OK
                    rec.trips = 0
                logger.warning(
                    "straggler probation decay for %s (model %s): %s -> %s "
                    "(%d sample-free windows — demotion starved it of the "
                    "traffic that could clear it)",
                    w, rec.model, rec.state, new, self.PROBATION_WINDOWS,
                )
                rec.state = new
                changed[w] = new
            if len(fresh) < self.policy.min_peers:
                continue  # no differential signal: everyone holds
            med = _median([self._workers[w].ewma for w in fresh])
            if med <= 0.0:
                continue
            cut = self.policy.factor * med
            for w in fresh:
                rec = self._workers[w]
                if rec.ewma > cut:
                    rec.trips += 1
                    self.trips_total += 1
                    new = (
                        CONFIRMED if rec.trips >= self.policy.trips
                        else SUSPECT
                    )
                else:
                    # one full window back in the peer envelope: clear
                    rec.trips = 0
                    new = OK
                if new != rec.state:
                    logger.warning(
                        "straggler verdict for %s (model %s): %s -> %s "
                        "(ewma %.1f us/tok, peer median %.1f, factor %.1f)",
                        w, rec.model, rec.state, new, rec.ewma, med,
                        self.policy.factor,
                    )
                    rec.state = new
                    changed[w] = new
        for rec in self._workers.values():
            rec.samples_at_window = rec.samples
        self._window_start = now
        return changed

    def verdicts(self) -> Dict[str, str]:
        """All current non-ok verdicts (re-put fodder for the store-sync
        loop after a statestore blip loses its leased keys)."""
        return {
            w: rec.state for w, rec in self._workers.items()
            if rec.state != OK
        }

    def state_of(self, worker_id: str) -> str:
        rec = self._workers.get(worker_id)
        return rec.state if rec is not None else OK

    def debug_dump(self) -> Dict[str, Any]:
        return {
            "windows_total": self.windows_total,
            "trips_total": self.trips_total,
            "workers": {
                w: {
                    "model": rec.model,
                    "ewma": round(rec.ewma, 1),
                    "samples": rec.samples,
                    "state": rec.state,
                    "trips": rec.trips,
                }
                for w, rec in self._workers.items()
            },
        }
