"""Performance attribution plane: where the microseconds go.

The flight recorder (PR5) and the SLO engine (PR6) can say *that* a
request was slow; nothing in the system could say *where* inside a
dispatch or an event-loop tick the time went — which is why the two
standing perf walls (the Pallas decode kernel losing to dense jnp, and
one frontend process capping at ~50k tok/s) have been guess-and-measure
loops since BENCH_r05. This module is the shared vocabulary of that
missing layer (docs/observability.md §Profiling):

- **ProfilePolicy** — the ``DYN_TPU_PROFILE*`` knob bundle (PR3 clamping
  contract). ``DYN_TPU_PROFILE`` defaults OFF and is THE zero-overhead
  gate: with it unset, no timeline ring, no frontend CPU accumulator and
  no event-loop lag sampler is ever constructed (tests monkeypatch the
  constructors to prove it), and the engine step loop pays one attribute
  check per dispatch.
- **StepTimeline** — a process-global, thread-safe ring of per-dispatch
  records fed by the engine step loop: phase (prefill ``chunk`` /
  ``decode`` / ``verify``), batch shape, *block-until-ready device time*
  vs *host-side dispatch overhead* (split again into pre-dispatch build
  and post-fetch emit work), allocator time (alloc/grow/evict/
  seal-checksum ride one accumulator), per-step queue depths, and the
  request/trace ids (PR5) riding the batch — plus ``jit_compile`` events
  with the triggering variant/shape detail. A decode-roofline decay like
  BENCH_r05's 0.31→0.17 becomes readable as "device idle between
  dispatches" vs "recompile storm" vs "allocator stall".
- **FrontendCpu / EventLoopLagSampler** — the frontend hot path's
  equivalents: per-token CPU split across detokenize / serialize /
  transport-write (the 19.8 µs/token residue, decomposed) and an
  event-loop lag sampler whose gauges the PR8 planner can consume.
- **Chrome-trace export** — :func:`to_chrome_trace` renders any record
  set as a Perfetto-loadable Chrome trace JSON (one track per engine
  phase, one per event loop, slice args carrying the PR5 ids), served by
  ``GET /debug/profile`` and ``llmctl profile capture --trace``.

Sampling: timing a dispatch costs a handful of ``perf_counter`` calls
plus one ``block_until_ready`` on the dispatch outputs (which, in
pipelined decode, serializes that one dispatch). ``sample_every`` bounds
the tax — only every Nth dispatch is timed; untimed dispatches still
count into ``dispatches_total`` so ``device_idle_frac`` stays honest
about coverage.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

ENV_PROFILE = "DYN_TPU_PROFILE"
ENV_SAMPLE = "DYN_TPU_PROFILE_SAMPLE"
ENV_RING = "DYN_TPU_PROFILE_RING"
ENV_LAG_MS = "DYN_TPU_PROFILE_LAG_MS"

# engine dispatch phases a timeline record may carry (the chrome-trace
# track names); free-form phases still record — these are the documented set
PHASES = ("chunk", "decode", "verify", "loop_lag")

# the PR3 clamping helpers live in the one shared home rather than being
# copied a fifth time — one clamping contract, one implementation
from dynamo_tpu.runtime.envknobs import (  # noqa: E402
    env_clamped_float as _env_clamped_float,
    env_clamped_int as _env_clamped_int,
    env_flag as _env_flag,
)


@dataclass(frozen=True)
class ProfilePolicy:
    """Knob bundle for the profiling plane (PR3 clamping contract:
    malformed / non-positive values fall back to defaults, in-range
    values clamp into the documented bounds).

    ``enabled``       DYN_TPU_PROFILE (default OFF — 1 arms the plane;
                      0/unset is the zero-overhead gate: nothing is ever
                      constructed).
    ``sample_every``  time every Nth engine dispatch (clamped to
                      [1, 1_000_000]; 1 = every dispatch — exact but the
                      block-until-ready serializes pipelined decode, so
                      production captures want the default 8).
    ``ring_size``     dispatch/event records retained (clamped to
                      [256, 262_144]).
    ``lag_ms``        event-loop lag sampler interval in ms (clamped to
                      [5, 10_000]).
    """

    enabled: bool = False
    sample_every: int = 8
    ring_size: int = 4096
    lag_ms: float = 100.0

    @classmethod
    def from_env(cls) -> "ProfilePolicy":
        d = cls()
        return cls(
            enabled=_env_flag(ENV_PROFILE, d.enabled),
            sample_every=_env_clamped_int(
                ENV_SAMPLE, d.sample_every, 1, 1_000_000
            ),
            ring_size=_env_clamped_int(ENV_RING, d.ring_size, 256, 262_144),
            lag_ms=_env_clamped_float(ENV_LAG_MS, d.lag_ms, 5.0, 10_000.0),
        )


def maybe_from_env() -> Optional[ProfilePolicy]:
    """The gate every integration point None-checks: ``None`` unless the
    profiling plane is armed — with ``DYN_TPU_PROFILE`` unset/0 no policy
    object is ever constructed (the PR9/PR13 zero-overhead pattern)."""
    if not _env_flag(ENV_PROFILE, False):
        return None
    return ProfilePolicy.from_env()


def enabled() -> bool:
    """Cheap boolean form of the gate (one env read, no object)."""
    return _env_flag(ENV_PROFILE, False)


def _pctl(sorted_vals: Sequence[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted sample list."""
    if not sorted_vals:
        return 0.0
    idx = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[idx]


# ---------------------------------------------------------------------------
# the engine-side dispatch timeline
# ---------------------------------------------------------------------------


class StepTimeline:
    """Process-global ring of per-dispatch timing records + events.

    Constructed lazily behind the :func:`maybe_from_env` gate — with
    profiling off nothing ever constructs it (the zero-overhead guard
    monkeypatches this constructor to prove it). Thread-safe: the engine
    step thread appends, the RPC/HTTP threads snapshot.

    A dispatch record is a plain dict (wire-ready for ``profile_dump``):

    ``ts``         epoch seconds of the dispatch's host-build start
                   (wall-clock so captures from different workers align
                   on one Perfetto timeline)
    ``phase``      "chunk" | "decode" | "verify"
    ``step``       the engine's step counter
    ``batch``      active lanes in the dispatch
    ``tokens``     tokens this dispatch advances (prefill feed or
                   batch × decode_steps)
    ``host_us``    host-side build time up to the jit call (alloc time
                   included; the "dispatch overhead" half of the split)
    ``device_us``  jit call → outputs ready (block-until-ready; the
                   device half)
    ``post_us``    host-side fetch/emit work after the outputs were
                   ready (still dispatch overhead, but attributable to
                   token processing, not building)
    ``alloc_us``   allocator share of host_us (alloc/grow/evict/
                   seal-checksum accumulated since the last record)
    ``queue``      pending + awaiting-remote-prefill depth at dispatch
    ``reqs``       up to 8 request ids riding the batch (PR5 link)
    ``traces``     their trace ids when tracing is on (PR5 link)
    """

    def __init__(self, policy: Optional[ProfilePolicy] = None):
        self._policy = policy or ProfilePolicy.from_env()
        self._lock = threading.Lock()
        self._records: deque = deque(maxlen=self._policy.ring_size)
        # event-loop lag samples ride their OWN ring: a frontend's ~10
        # samples/s must not evict engine dispatch records or count into
        # sampled_total (a co-hosted engine+frontend shares this object)
        self._lag_records: deque = deque(
            maxlen=min(self._policy.ring_size, 4096)
        )
        self._events: deque = deque(maxlen=min(self._policy.ring_size, 1024))
        self._sample_ctr = 0
        self.dispatches_total = 0
        self.sampled_total = 0
        self.jit_compiles_total = 0

    @property
    def policy(self) -> ProfilePolicy:
        return self._policy

    def should_sample(self) -> bool:
        """One call per dispatch: counts it and decides whether this one
        pays the timing tax (every ``sample_every``-th does)."""
        with self._lock:
            self.dispatches_total += 1
            self._sample_ctr += 1
            if self._sample_ctr >= self._policy.sample_every:
                self._sample_ctr = 0
                return True
            return False

    def note_dispatch(
        self,
        phase: str,
        *,
        step: int = 0,
        batch: int = 0,
        tokens: int = 0,
        host_us: float = 0.0,
        device_us: float = 0.0,
        post_us: float = 0.0,
        alloc_us: float = 0.0,
        queue: int = 0,
        reqs: Sequence[str] = (),
        traces: Sequence[str] = (),
        ts: Optional[float] = None,
    ) -> None:
        rec = {
            # epoch-aligned so multi-worker captures merge onto one
            # Perfetto timeline
            "ts": float(ts) if ts is not None else time.time(),  # dynlint: allow-wall-clock(cross-process trace alignment)
            "phase": str(phase),
            "step": int(step),
            "batch": int(batch),
            "tokens": int(tokens),
            "host_us": round(float(host_us), 1),
            "device_us": round(float(device_us), 1),
            "post_us": round(float(post_us), 1),
            "alloc_us": round(float(alloc_us), 1),
            "queue": int(queue),
        }
        if reqs:
            rec["reqs"] = list(reqs)[:8]
        if traces:
            rec["traces"] = list(traces)[:8]
        with self._lock:
            if phase == "loop_lag":
                self._lag_records.append(rec)
            else:
                self._records.append(rec)
                self.sampled_total += 1

    def note_event(self, kind: str, detail: str = "", phase: str = "") -> None:
        ev = {
            "ts": time.time(),  # dynlint: allow-wall-clock(cross-process trace alignment)
            "kind": str(kind),
            "detail": str(detail),
        }
        if phase:
            ev["phase"] = phase
        with self._lock:
            self._events.append(ev)
            if kind == "jit_compile":
                self.jit_compiles_total += 1

    # -- reads --------------------------------------------------------------

    def records(self, since_s: Optional[float] = None) -> List[dict]:
        with self._lock:
            out = list(self._records) + list(self._lag_records)
        out.sort(key=lambda r: r["ts"])
        if since_s is not None and since_s > 0:
            cutoff = time.time() - since_s  # dynlint: allow-wall-clock(records carry epoch ts)
            out = [r for r in out if r["ts"] >= cutoff]
        return out

    def events(self, since_s: Optional[float] = None) -> List[dict]:
        with self._lock:
            out = list(self._events)
        if since_s is not None and since_s > 0:
            cutoff = time.time() - since_s  # dynlint: allow-wall-clock(events carry epoch ts)
            out = [e for e in out if e["ts"] >= cutoff]
        return out

    def summary(self, since_s: Optional[float] = None) -> Dict[str, Any]:
        """Per-phase device/host quantiles + the idle fraction — the
        "read device_idle_frac first" number of the runbook."""
        recs = self.records(since_s)
        phases: Dict[str, Dict[str, List[float]]] = {}
        for r in recs:
            p = phases.setdefault(
                r["phase"],
                {"device": [], "host": [], "alloc": [], "tokens": []},
            )
            p["device"].append(r["device_us"])
            p["host"].append(r["host_us"] + r["post_us"])
            p["alloc"].append(r["alloc_us"])
            p["tokens"].append(r["tokens"])
        out: Dict[str, Any] = {
            "dispatches_total": self.dispatches_total,
            "sampled_total": self.sampled_total,
            "jit_compiles_total": self.jit_compiles_total,
            "phases": {},
        }
        for name, p in phases.items():
            dev = sorted(p["device"])
            host = sorted(p["host"])
            out["phases"][name] = {
                "count": len(dev),
                "device_us_p50": round(_pctl(dev, 0.50), 1),
                "device_us_p95": round(_pctl(dev, 0.95), 1),
                "host_us_p50": round(_pctl(host, 0.50), 1),
                "host_us_p95": round(_pctl(host, 0.95), 1),
                "alloc_us_p95": round(_pctl(sorted(p["alloc"]), 0.95), 1),
                "tokens": int(sum(p["tokens"])),
            }
        out["device_idle_frac"] = self.device_idle_frac(recs)
        return out

    @staticmethod
    def device_idle_frac(recs: List[dict]) -> float:
        """Fraction of the sampled wall span the device spent NOT
        executing a dispatch. Computed over consecutive *sampled*
        engine-phase records (loop_lag and events excluded): each pair's
        busy time is the earlier record's device time scaled by the step
        delta between them — at a sampling stride of N, the N-1 unsampled
        dispatches in the gap are assumed device-shaped like the sampled
        one (capped at the gap), so the default stride doesn't read a
        fully-busy device as mostly idle."""
        eng = [r for r in recs if r["phase"] in ("chunk", "decode", "verify")]
        if len(eng) < 2:
            return 0.0
        eng.sort(key=lambda r: r["ts"])
        busy = 0.0
        span = 0.0
        for a, b in zip(eng, eng[1:]):
            stride = b["step"] - a["step"]
            gap = b["ts"] - a["ts"]
            if stride <= 0 or gap <= 0:
                continue  # step-counter reset (engine restart) or clock skew
            span += gap
            busy += min(a["device_us"] * stride / 1e6, gap)
        if span <= 0:
            return 0.0
        return round(min(max(1.0 - busy / span, 0.0), 1.0), 4)

    # recent-tail bound for the per-tick gauge computation: plenty of
    # samples for a p95, and the cost stays flat at the max ring size
    GAUGE_WINDOW = 2048

    def gauges(self) -> Dict[str, float]:
        """The worker-gauge view (ForwardPassMetrics fields): decode-phase
        p95 split + idle fraction. Runs on the ~1 s metrics loop, so it
        reads only the most recent :data:`GAUGE_WINDOW` engine records —
        a max-size ring (262k records) must not cost a full copy + sort
        per tick inside the plane whose own overhead budget is <2%."""
        with self._lock:
            n = len(self._records)
            recs = list(
                self._records
            ) if n <= self.GAUGE_WINDOW else [
                self._records[i] for i in range(n - self.GAUGE_WINDOW, n)
            ]
        dev: List[float] = []
        host: List[float] = []
        for r in recs:
            if r["phase"] == "decode":
                dev.append(r["device_us"])
                host.append(r["host_us"] + r["post_us"])
        dev.sort()
        host.sort()
        return {
            "dispatch_device_us_p95": round(_pctl(dev, 0.95), 1),
            "dispatch_host_overhead_us_p95": round(_pctl(host, 0.95), 1),
            "device_idle_frac": self.device_idle_frac(recs),
        }


# ---------------------------------------------------------------------------
# the frontend-side hot-path accounting
# ---------------------------------------------------------------------------


class FrontendCpu:
    """Per-token CPU attribution for the frontend hot path: detokenize /
    serialize / transport-write, cumulative per part with each part's own
    token count (the stages live in different pipeline layers — a
    detokenizer-only process must not divide by the SSE writer's count).
    Constructed lazily behind the gate (zero-overhead guard monkeypatches
    the constructor); the lock only serializes the cross-thread
    ``/metrics`` read against the event-loop writers."""

    PARTS = ("detokenize", "serialize", "transport_write")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._us: Dict[str, float] = {p: 0.0 for p in self.PARTS}
        self._tokens: Dict[str, int] = {p: 0 for p in self.PARTS}

    def note(self, part: str, us: float, tokens: int = 0) -> None:
        with self._lock:
            self._us[part] = self._us.get(part, 0.0) + us
            self._tokens[part] = self._tokens.get(part, 0) + tokens

    def per_token(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {}
            for part in self._us:
                n = self._tokens.get(part, 0)
                out[part] = round(self._us[part] / max(n, 1), 3)
            out["tokens"] = dict(self._tokens)
            return out


class EventLoopLagSampler:
    """Measures how late ``asyncio.sleep(interval)`` wakes on this event
    loop — the direct signal of a saturated frontend process (the ~50k
    tok/s wall shows up here before it shows up in ITL). Keeps an EMA and
    the peak; samples also land in the timeline (phase ``loop_lag``) so
    ``--trace`` captures render the event loop as its own track."""

    def __init__(self, interval_s: float = 0.1,
                 timeline: Optional[StepTimeline] = None):
        self.interval_s = max(float(interval_s), 0.005)
        self.lag_ema_ms = 0.0
        self.lag_max_ms = 0.0
        self.samples = 0
        self._timeline = timeline
        self._task = None
        # start/stop are refcounted: the sampler is process-global and
        # co-hosted services share it — one service stopping must not
        # kill the lag gauges of the others still running
        self._starts = 0

    async def _run(self) -> None:
        import asyncio

        while True:
            t0 = time.perf_counter()
            await asyncio.sleep(self.interval_s)
            lag_ms = max(
                (time.perf_counter() - t0 - self.interval_s) * 1e3, 0.0
            )
            self.samples += 1
            self.lag_ema_ms = (
                lag_ms if self.samples == 1
                else self.lag_ema_ms + 0.2 * (lag_ms - self.lag_ema_ms)
            )
            if lag_ms > self.lag_max_ms:
                self.lag_max_ms = lag_ms
            if self._timeline is not None:
                self._timeline.note_dispatch(
                    "loop_lag", host_us=lag_ms * 1e3,
                )

    def start(self):
        import asyncio

        self._starts += 1
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(self._run())
        return self._task

    def stop(self) -> None:
        self._starts = max(self._starts - 1, 0)
        if self._starts == 0 and self._task is not None:
            self._task.cancel()
            self._task = None

    def gauges(self) -> Dict[str, float]:
        return {
            "ema_ms": round(self.lag_ema_ms, 3),
            "max_ms": round(self.lag_max_ms, 3),
            "samples": self.samples,
        }


# ---------------------------------------------------------------------------
# process-global accessors (constructor-free reads, lazy writes)
# ---------------------------------------------------------------------------

_TIMELINE: Optional[StepTimeline] = None
_FRONTEND: Optional[FrontendCpu] = None
_LAG: Optional[EventLoopLagSampler] = None
_LOCK = threading.Lock()


def timeline() -> StepTimeline:
    """The process-global timeline, constructed on first use — callers
    sit behind the :func:`maybe_from_env` gate, so with profiling off
    nothing ever calls this."""
    global _TIMELINE
    if _TIMELINE is None:
        with _LOCK:
            if _TIMELINE is None:
                _TIMELINE = StepTimeline()
    return _TIMELINE


def maybe_timeline() -> Optional[StepTimeline]:
    """Constructor-free read: None until something armed the plane."""
    return _TIMELINE


def frontend_cpu() -> FrontendCpu:
    global _FRONTEND
    if _FRONTEND is None:
        with _LOCK:
            if _FRONTEND is None:
                _FRONTEND = FrontendCpu()
    return _FRONTEND


def maybe_frontend_cpu() -> Optional[FrontendCpu]:
    return _FRONTEND


def lag_sampler(interval_s: Optional[float] = None) -> EventLoopLagSampler:
    """The process's event-loop lag sampler (one per process: co-hosted
    services share the loop, so they share the lag)."""
    global _LAG
    if _LAG is None:
        # resolve the timeline BEFORE taking the module lock: timeline()
        # takes the same non-reentrant lock
        tl = timeline()
        with _LOCK:
            if _LAG is None:
                pol = ProfilePolicy.from_env()
                _LAG = EventLoopLagSampler(
                    interval_s if interval_s is not None
                    else pol.lag_ms / 1e3,
                    timeline=tl,
                )
    return _LAG


def maybe_lag_sampler() -> Optional[EventLoopLagSampler]:
    return _LAG


def note_event(kind: str, detail: str = "", phase: str = "") -> None:
    """Constructor-free event feed (``compile_cache.record_compile``
    forwards here): a no-op until something armed the timeline."""
    t = _TIMELINE
    if t is not None:
        t.note_event(kind, detail, phase)


def gauges() -> Dict[str, float]:
    """Constructor-free worker-gauge read for the metrics publisher:
    empty dict until the plane was ever armed in this process."""
    t = _TIMELINE
    if t is None:
        return {}
    return t.gauges()


def dump_state(since_s: Optional[float] = None) -> Dict[str, Any]:
    """The ``profile_dump`` RPC / ``GET /debug/profile`` payload —
    constructor-free; a process that never armed profiling answers with
    ``enabled: false`` and empty sections."""
    t = _TIMELINE
    out: Dict[str, Any] = {"enabled": enabled()}
    if t is not None:
        out["summary"] = t.summary(since_s)
        out["records"] = t.records(since_s)
        out["events"] = t.events(since_s)
    else:
        out["summary"] = {}
        out["records"] = []
        out["events"] = []
    fc = _FRONTEND
    if fc is not None:
        out["frontend_cpu_us_per_token"] = fc.per_token()
    lag = _LAG
    if lag is not None:
        out["event_loop_lag_ms"] = lag.gauges()
    return out


def render_frontend_prometheus(prefix: str = "dynamo_frontend") -> str:
    """Frontend hot-path gauges for the /metrics exposition —
    constructor-free, empty string until anything was recorded."""
    lines: List[str] = []
    fc = _FRONTEND
    if fc is not None:
        per = fc.per_token()
        full = f"{prefix}_cpu_us_per_token"
        lines.append(
            f"# HELP {full} Frontend hot-path CPU microseconds per "
            f"streamed token, by pipeline part"
        )
        lines.append(f"# TYPE {full} gauge")
        for part in FrontendCpu.PARTS:
            lines.append(f'{full}{{part="{part}"}} {per[part]}')
    lag = _LAG
    if lag is not None:
        g = lag.gauges()
        full = f"{prefix}_event_loop_lag_ms"
        lines.append(
            f"# HELP {full} Event-loop wakeup lag (scheduling delay) in ms"
        )
        lines.append(f"# TYPE {full} gauge")
        lines.append(f'{full}{{stat="ema"}} {g["ema_ms"]}')
        lines.append(f'{full}{{stat="max"}} {g["max_ms"]}')
    return "\n".join(lines) + ("\n" if lines else "")


def reset_for_tests() -> None:
    """Drop the process-global state (conftest autouse reset: one test's
    records/lag samples must not bleed into another's assertions)."""
    global _TIMELINE, _FRONTEND, _LAG
    with _LOCK:
        if _LAG is not None:
            _LAG._starts = 0  # force past the refcount: tests must not leak
            if _LAG._task is not None:
                _LAG._task.cancel()
                _LAG._task = None
        _TIMELINE = None
        _FRONTEND = None
        _LAG = None


# ---------------------------------------------------------------------------
# Chrome-trace (Perfetto-loadable) export
# ---------------------------------------------------------------------------

# stable track ids per phase so multi-capture merges stay aligned
_TRACK_IDS = {"chunk": 1, "decode": 2, "verify": 3, "loop_lag": 8}
_HOST_TRACK = 6
_EVENT_TRACK = 7


def to_chrome_trace(
    captures: Iterable[Tuple[str, List[dict], List[dict]]],
) -> Dict[str, Any]:
    """Render captures as a Chrome-trace JSON object (Perfetto loads it
    directly; ``chrome://tracing`` too).

    ``captures`` is an iterable of ``(process_name, records, events)`` —
    one entry per worker/frontend. Layout: one *process* per capture, one
    *track* (tid) per engine phase plus a ``host`` track (pre-build and
    post-emit slices), an ``events`` track (jit compiles as instant
    events) and an ``event_loop`` track for lag samples. ``ts``/``dur``
    are microseconds since the earliest record across all captures.

    Slices on a track are emitted sorted and non-overlapping: a slice
    whose start precedes the previous slice's end is clamped forward (in
    pipelined decode the next dispatch is *queued* while the previous
    executes — the clamped start is when the device actually got to it).
    """
    caps = [
        (name, list(records), list(events)) for name, records, events in captures
    ]
    t0 = min(
        (
            r["ts"]
            for _, records, events in caps
            for r in list(records) + list(events)
        ),
        default=0.0,
    )

    trace_events: List[dict] = []
    for pid, (name, records, events) in enumerate(caps, start=1):
        trace_events.append({
            "ph": "M", "pid": pid, "tid": 0, "name": "process_name",
            "args": {"name": name},
        })
        named_tracks = dict(_TRACK_IDS)
        for phase, tid in sorted(named_tracks.items()):
            label = "event_loop" if phase == "loop_lag" else f"engine/{phase}"
            trace_events.append({
                "ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
                "args": {"name": label},
            })
        trace_events.append({
            "ph": "M", "pid": pid, "tid": _HOST_TRACK, "name": "thread_name",
            "args": {"name": "engine/host"},
        })
        trace_events.append({
            "ph": "M", "pid": pid, "tid": _EVENT_TRACK, "name": "thread_name",
            "args": {"name": "engine/events"},
        })

        # bucket slices per track, then clamp each track independently
        per_track: Dict[int, List[dict]] = {}
        for r in sorted(records, key=lambda r: r["ts"]):
            base_us = (r["ts"] - t0) * 1e6
            phase = r["phase"]
            tid = named_tracks.get(phase)
            if tid is None:
                tid = named_tracks[phase] = 16 + len(named_tracks)
                trace_events.append({
                    "ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
                    "args": {"name": f"engine/{phase}"},
                })
            args = {
                k: r[k]
                for k in ("step", "batch", "tokens", "queue", "alloc_us",
                          "reqs", "traces")
                if k in r and r[k]
            }
            if phase == "loop_lag":
                # lag sample: one slice whose duration IS the lag
                per_track.setdefault(tid, []).append({
                    "ph": "X", "pid": pid, "tid": tid, "name": "loop_lag",
                    "ts": base_us, "dur": max(r["host_us"], 1.0),
                    "args": args,
                })
                continue
            host_end = base_us + r["host_us"]
            dev_end = host_end + r["device_us"]
            if r["host_us"] > 0:
                per_track.setdefault(_HOST_TRACK, []).append({
                    "ph": "X", "pid": pid, "tid": _HOST_TRACK,
                    "name": f"{phase}.build", "ts": base_us,
                    "dur": r["host_us"], "args": args,
                })
            per_track.setdefault(tid, []).append({
                "ph": "X", "pid": pid, "tid": tid, "name": phase,
                "ts": host_end, "dur": max(r["device_us"], 1.0),
                "args": args,
            })
            if r.get("post_us", 0) > 0:
                per_track.setdefault(_HOST_TRACK, []).append({
                    "ph": "X", "pid": pid, "tid": _HOST_TRACK,
                    "name": f"{phase}.emit", "ts": dev_end,
                    "dur": r["post_us"], "args": args,
                })
        for tid, slices in per_track.items():
            slices.sort(key=lambda s: s["ts"])
            prev_end = -1.0
            for s in slices:
                if s["ts"] < prev_end:
                    # queued behind the previous slice on this track
                    shift = prev_end - s["ts"]
                    s["ts"] = prev_end
                    s["dur"] = max(s["dur"] - shift, 1.0)
                s["ts"] = round(s["ts"], 1)
                s["dur"] = round(s["dur"], 1)
                prev_end = s["ts"] + s["dur"]
                trace_events.append(s)
        for e in sorted(events, key=lambda e: e["ts"]):
            trace_events.append({
                "ph": "i", "pid": pid, "tid": _EVENT_TRACK,
                "name": e["kind"], "ts": round((e["ts"] - t0) * 1e6, 1),
                "s": "t", "args": {"detail": e.get("detail", "")},
            })
    return {
        "traceEvents": trace_events,
        "displayTimeUnit": "ms",
        "otherData": {
            "source": "dynamo_tpu profiling plane (llmctl profile capture)",
            "epoch_t0": t0,
        },
    }


def chrome_trace_json(
    captures: Iterable[Tuple[str, List[dict], List[dict]]],
) -> str:
    return json.dumps(to_chrome_trace(captures))
