"""Multi-tenant QoS: tenant identity, classes, rate limits, fair queuing.

Millions of users on shared chips is a *contention* problem: PR3's
admission control bounds the total queue, but first-come-first-served
admission still lets one abusive (or merely long-prompt) tenant consume
every slot, every KV block, and every prefill dispatch — degrading every
other tenant's TTFT/ITL while staying inside the global budget. This
module is the policy core the QoS plane shares:

- **Tenant identity** — a string id extracted at the HTTP edge
  (``x-tenant-id`` header, or an API-key map) that rides
  ``EngineContext.tenant`` and the RPC request header end to end. No
  header and no knobs ⇒ the default single-tenant path, which pays one
  None-check everywhere (asserted by the tests/test_qos.py overhead
  guard).
- **Tenant classes** (:class:`QosPolicy`) — named weight tiers
  (``batch:1,standard:4,premium:16`` by default) with a tenant→class map;
  the weight scales every other budget (rate, burst, fair-queue share).
- **Token-bucket rate limits** (:class:`TenantRateLimiter`) — per-tenant
  request buckets; an over-rate tenant is shed with a *per-tenant*
  ``Retry-After`` (time until its own bucket refills) instead of a global
  hint. The tenant table is LRU-bounded so spoofed ``x-tenant-id`` floods
  cannot grow worker memory.
- **Weighted fair queuing** (:class:`FairQueue`) — virtual-time
  bookkeeping (start-time fair queuing): each tenant's virtual clock
  advances by ``cost / weight`` as its work is served; the scheduler
  always picks the pending tenant with the *smallest* virtual time, so a
  starved tenant (large deficit) is preferred no matter how deep a noisy
  neighbor's backlog is.
- **Prefill budgeting** (:func:`split_prefill_budget`) — the per-step
  token budget (``DYN_TPU_PREFILL_BUDGET``) that chunked prefill in the
  aggregated engine divides across prefilling lanes so long prompts raise
  their *own* TTFT instead of spiking every decode lane's ITL.

All knobs are ``DYN_TPU_TENANT_*`` env vars with the PR3 clamping
contract (malformed/zero/negative → defaults; see
:meth:`QosPolicy.from_env`). ``maybe_from_env()`` returns ``None`` when no
knob is set — the hook every hot path gates on.

Reference analogue: the dynamo paper's KV block manager reuse *tiers* and
priority-aware reuse exist for exactly this shared-chip contention;
here the same priority notion also drives admission and scheduling.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# every knob this plane reads; maybe_from_env() gates on their presence
ENV_PREFIX = "DYN_TPU_TENANT_"

# the tenant id used when QoS is enabled but a request arrives without
# any identity (no header, no key map hit): anonymous traffic shares one
# bucket/queue instead of bypassing QoS entirely
DEFAULT_TENANT = "default"


# knob parsers live in the one shared home (runtime/envknobs.py)
from dynamo_tpu.runtime.envknobs import (  # noqa: E402
    env_nonneg_float as _env_nonneg_float,
    env_nonneg_int as _env_nonneg_int,
    env_pos_float as _env_pos_float,
    env_pos_int as _env_pos_int,
    env_str as _env_str,
)


def env_prefill_budget(default: int = 0) -> int:
    """``DYN_TPU_PREFILL_BUDGET``: max prefill tokens one engine step may
    compute across all prefilling lanes (0 = unlimited, the pre-QoS
    behavior). Malformed/negative values clamp to the default — a bad
    value must degrade to "no budget", never to a budget of 0 tokens
    that would livelock every prefill."""
    return _env_nonneg_int("DYN_TPU_PREFILL_BUDGET", default)


def _parse_classes(raw: str) -> "OrderedDict[str, float]":
    """``name:weight,name:weight`` → ordered name→weight. Malformed
    entries are skipped (one typo must not take down the whole class
    table); non-positive weights clamp to 1."""
    out: "OrderedDict[str, float]" = OrderedDict()
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, w = part.partition(":")
        name = name.strip()
        if not name:
            continue
        try:
            weight = float(w) if w.strip() else 1.0
        except ValueError:
            weight = 1.0
        out[name] = weight if weight > 0 else 1.0
    return out


def _parse_map(raw: str) -> Dict[str, str]:
    """``key=value,key=value`` → dict; entries without ``=`` are skipped."""
    out: Dict[str, str] = {}
    for part in raw.split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        k, _, v = part.partition("=")
        k, v = k.strip(), v.strip()
        if k and v:
            out[k] = v
    return out


_DEFAULT_CLASSES = "batch:1,standard:4,premium:16"


@dataclass
class QosPolicy:
    """The tenant-QoS knob bundle (``QosPolicy.from_env()``).

    ``classes``        ordered class name → weight (scheduling share and
                       the multiplier on rate/burst). Levels — the
                       KV-eviction/preemption priority — are the index in
                       this table (first class = level 0 = evicted
                       first), so the operator's declaration order IS the
                       preemption order.
    ``tenant_map``     tenant id → class name; unmapped tenants get
                       ``default_class``.
    ``key_map``        API key (``Authorization`` bearer value) → tenant
                       id, for edges whose callers can't set headers.
    ``default_class``  class for unmapped tenants (clamped to a declared
                       class; falls back to the last — highest-weight —
                       declared class if the named one doesn't exist,
                       so a typo'd default never zeroes everyone's
                       priority).
    ``rate_rps``       token-bucket refill in requests/s *per weight
                       unit* (a weight-16 tenant refills 16× faster).
                       0 = rate limiting disabled.
    ``burst``          bucket capacity per weight unit.
    ``kv_frac``        max fraction of the KV pool one tenant may hold
                       while other tenants are active (0 = disabled).
    ``slot_frac``      max fraction of the decode slots one tenant may
                       occupy while other tenants are active (0 =
                       disabled). Work-conserving like ``kv_frac``: a
                       tenant alone on the engine may use every slot.
    ``max_tenants``    LRU bound on tracked tenants (spoofed ids must
                       not grow memory).
    ``unmapped``       how to treat tenant ids the operator did NOT
                       declare (not in ``tenant_map``, not minted by the
                       key map): ``per-id`` (default — each gets its own
                       default-class bucket; for trusted edges behind an
                       authenticating gateway) or ``shared`` (they all
                       collapse into the default tenant, so rotating a
                       spoofed ``x-tenant-id`` per request cannot mint
                       fresh burst tokens). Any other value degrades to
                       ``per-id``.
    """

    classes: "OrderedDict[str, float]" = field(
        default_factory=lambda: _parse_classes(_DEFAULT_CLASSES)
    )
    tenant_map: Dict[str, str] = field(default_factory=dict)
    key_map: Dict[str, str] = field(default_factory=dict)
    default_class: str = "standard"
    rate_rps: float = 0.0
    burst: float = 4.0
    kv_frac: float = 0.0
    slot_frac: float = 0.0
    max_tenants: int = 1024
    unmapped: str = "per-id"

    def __post_init__(self) -> None:
        if not self.classes:
            self.classes = _parse_classes(_DEFAULT_CLASSES)
        if self.default_class not in self.classes:
            self.default_class = next(reversed(self.classes))
        self.kv_frac = min(max(self.kv_frac, 0.0), 1.0)
        self.slot_frac = min(max(self.slot_frac, 0.0), 1.0)
        if self.unmapped not in ("per-id", "shared"):
            self.unmapped = "per-id"
        # class name → (level, weight); level = declaration order
        self._levels: Dict[str, Tuple[int, float]] = {
            name: (i, w) for i, (name, w) in enumerate(self.classes.items())
        }

    @classmethod
    def from_env(cls, prefix: str = ENV_PREFIX) -> "QosPolicy":
        d = cls()
        return cls(
            classes=_parse_classes(
                _env_str(prefix + "CLASSES", _DEFAULT_CLASSES)
            ),
            tenant_map=_parse_map(_env_str(prefix + "MAP", "")),
            key_map=_parse_map(_env_str(prefix + "KEYS", "")),
            default_class=_env_str(prefix + "DEFAULT_CLASS", d.default_class),
            rate_rps=_env_nonneg_float(prefix + "RATE", d.rate_rps),
            burst=_env_pos_float(prefix + "BURST", d.burst),
            kv_frac=_env_nonneg_float(prefix + "KV_FRAC", d.kv_frac),
            slot_frac=_env_nonneg_float(prefix + "SLOT_FRAC", d.slot_frac),
            max_tenants=_env_pos_int(prefix + "MAX", d.max_tenants),
            unmapped=_env_str(prefix + "UNMAPPED", d.unmapped),
        )

    def class_of(self, tenant: Optional[str]) -> Tuple[int, float]:
        """(level, weight) for a tenant id. Unknown tenants and the
        default tenant get ``default_class``."""
        cname = self.tenant_map.get(tenant or "", self.default_class)
        got = self._levels.get(cname)
        if got is None:  # mapped to an undeclared class: use the default
            got = self._levels[self.default_class]
        return got

    def class_name_of(self, tenant: Optional[str]) -> str:
        cname = self.tenant_map.get(tenant or "", self.default_class)
        return cname if cname in self._levels else self.default_class

    def tenant_of_key(self, authorization: Optional[str]) -> Optional[str]:
        """Map an ``Authorization`` header to a tenant id. Accepts the
        bare key or the ``Bearer <key>`` form."""
        if not authorization or not self.key_map:
            return None
        key = authorization.strip()
        if key.lower().startswith("bearer "):
            key = key[7:].strip()
        return self.key_map.get(key)

    def resolve_tenant(
        self,
        header_tenant: Optional[str],
        authorization: Optional[str] = None,
    ) -> str:
        """Edge identity resolution. The AUTHENTICATED binding (API-key
        map) wins over the client-supplied ``x-tenant-id`` header — a
        caller must not be able to bill another tenant's quota by setting
        a header its key contradicts. Undeclared header ids are kept
        per-id (trusted edge) or collapsed into the default tenant
        (``unmapped="shared"``: spoofed/rotating ids cannot mint fresh
        burst tokens). Anonymous traffic is always the default tenant."""
        tenant = self.tenant_of_key(authorization)
        if tenant is None:
            tenant = header_tenant
            if (
                tenant
                and self.unmapped == "shared"
                and tenant not in self.tenant_map
            ):
                tenant = DEFAULT_TENANT
        return tenant or DEFAULT_TENANT


def qos_env_set() -> bool:
    """Any ``DYN_TPU_TENANT_*`` knob set non-empty?"""
    return any(
        v for k, v in os.environ.items() if k.startswith(ENV_PREFIX)
    )


def maybe_from_env() -> Optional[QosPolicy]:
    """The gate every hot path uses: ``None`` (single-tenant, zero QoS
    bookkeeping) unless at least one ``DYN_TPU_TENANT_*`` knob is set."""
    return QosPolicy.from_env() if qos_env_set() else None


class TokenBucket:
    """Monotonic-clock token bucket. ``take()`` returns 0.0 when a token
    was consumed, else the seconds until one becomes available (the
    per-tenant ``Retry-After``)."""

    __slots__ = ("rate", "capacity", "tokens", "_t")

    def __init__(self, rate: float, capacity: float,
                 now: Optional[float] = None):
        self.rate = max(rate, 1e-9)
        self.capacity = max(capacity, 1.0)
        self.tokens = self.capacity
        self._t = time.monotonic() if now is None else now

    def take(self, now: Optional[float] = None, cost: float = 1.0) -> float:
        now = time.monotonic() if now is None else now
        if now > self._t:
            self.tokens = min(
                self.capacity, self.tokens + (now - self._t) * self.rate
            )
        self._t = now
        if self.tokens >= cost:
            self.tokens -= cost
            return 0.0
        return (cost - self.tokens) / self.rate


class TenantRateLimiter:
    """Per-tenant token buckets + admit/shed counters, LRU-bounded.

    Thread-safe (the HTTP edge and the RPC accept loop are async, but the
    engine publishes stats from its own thread). Buckets refill at
    ``rate_rps × class weight`` and hold ``burst × weight`` tokens, so a
    premium tenant's burst headroom scales with its share.
    """

    def __init__(self, policy: QosPolicy,
                 clock: Callable[[], float] = time.monotonic):
        self.policy = policy
        self.clock = clock
        self._lock = threading.Lock()
        self._buckets: "OrderedDict[str, TokenBucket]" = OrderedDict()
        # tenant → [admitted, rate_limited] cumulative counters (telemetry)
        self._stats: "OrderedDict[str, List[int]]" = OrderedDict()

    def take(self, tenant: Optional[str]) -> float:
        """0.0 = admitted; >0 = shed, value is the tenant's retry-after
        in seconds."""
        t = tenant or DEFAULT_TENANT
        now = self.clock()
        with self._lock:
            bucket = self._buckets.get(t)
            if bucket is None:
                _, weight = self.policy.class_of(t)
                bucket = TokenBucket(
                    self.policy.rate_rps * weight,
                    self.policy.burst * weight,
                    now=now,
                )
                self._buckets[t] = bucket
                while len(self._buckets) > self.policy.max_tenants:
                    self._buckets.popitem(last=False)
            else:
                self._buckets.move_to_end(t)
            wait = bucket.take(now)
            st = self._stats.get(t)
            if st is None:
                st = self._stats[t] = [0, 0]
                while len(self._stats) > self.policy.max_tenants:
                    self._stats.popitem(last=False)
            else:
                # true LRU like the bucket table: under tenant-id churn
                # the entry evicted must be the stalest, never a live
                # long-lived tenant's cumulative counters (whose reset
                # would run dynamo_tenant_*_total backwards)
                self._stats.move_to_end(t)
            st[0 if wait == 0.0 else 1] += 1
            return wait

    def stats(self) -> Dict[str, Dict[str, int]]:
        """{tenant: {"admitted": n, "rate_limited": n}} (cumulative)."""
        with self._lock:
            return {
                t: {"admitted": s[0], "rate_limited": s[1]}
                for t, s in self._stats.items()
            }


class FairQueue:
    """Weighted virtual-time fairness bookkeeping (start-time fair
    queuing, minus the per-packet finish tags — request costs here are
    only known as they stream).

    Each tenant carries a virtual time that advances by ``cost/weight``
    as its work is served. :meth:`pick` returns the candidate whose
    tenant has the smallest virtual time — the most-starved tenant by
    weighted share. A newly-seen (or long-idle) tenant's clock is lifted
    to the current minimum so it gets its fair share *from now on*
    rather than an unbounded credit for history it slept through; equal
    virtual times break toward the tenant with the least total service
    (so a newcomer joining at the floor still beats a backlog owner),
    then FIFO. The table is hard-bounded at ``max_tenants`` — a busy
    engine fed rotating spoofed tenant ids must not grow memory; past
    the cap the MOST-served clock is dropped (it rejoins at the floor if
    that tenant returns, a bounded fairness distortion). Engine-thread
    only (no locking).
    """

    __slots__ = ("_vt", "_served", "max_tenants")

    def __init__(self, max_tenants: int = 1024) -> None:
        self._vt: Dict[str, float] = {}
        self._served: Dict[str, float] = {}
        self.max_tenants = max(int(max_tenants), 1)

    def _floor(self) -> float:
        return min(self._vt.values()) if self._vt else 0.0

    def touch(self, tenant: str) -> None:
        if tenant not in self._vt:
            if len(self._vt) >= self.max_tenants:
                drop = max(self._vt, key=lambda t: (self._vt[t], t))
                del self._vt[drop]
                self._served.pop(drop, None)
            self._vt[tenant] = self._floor()
            self._served.setdefault(tenant, 0.0)

    def charge(self, tenant: str, cost: float, weight: float) -> None:
        self.touch(tenant)
        self._vt[tenant] += cost / max(weight, 1e-9)
        self._served[tenant] += cost

    def pick(self, tenants: Sequence[str]) -> int:
        """Index of the candidate whose tenant is most starved."""
        best_i = 0
        best_key = None
        for i, t in enumerate(tenants):
            self.touch(t)
            key = (self._vt[t], self._served[t])
            if best_key is None or key < best_key:
                best_i, best_key = i, key
        return best_i

    def vt(self, tenant: str) -> float:
        """Current virtual time of a tenant (registering it if new)."""
        self.touch(tenant)
        return self._vt[tenant]

    def virtual_times(self) -> Dict[str, float]:
        return dict(self._vt)

    def forget_absent(self, live: Sequence[str]) -> None:
        """Drop clocks of tenants with no live work (bounded memory on
        tenant churn); survivors keep their relative positions."""
        keep = set(live)
        self._vt = {t: v for t, v in self._vt.items() if t in keep}
        self._served = {t: v for t, v in self._served.items() if t in keep}


def split_prefill_budget(
    remaining: Sequence[int], chunk: int, budget: int
) -> List[int]:
    """Divide a per-step prefill token budget across prefilling lanes.

    ``remaining[i]`` = prompt tokens lane *i* still needs; lanes are
    given in scheduling-priority order (most-starved tenant first — the
    caller sorts). Returns per-lane allowances. ``budget <= 0`` means
    unlimited (every lane gets up to a full chunk — the pre-QoS
    behavior). The first lane is always allowed at least one token so a
    budget smaller than one lane's need can never livelock prefill; a
    lane may receive 0 (it simply doesn't advance this step)."""
    if budget <= 0:
        return [min(chunk, max(r, 0)) for r in remaining]
    allow: List[int] = []
    left = budget
    for i, r in enumerate(remaining):
        n = min(chunk, max(r, 0), max(left, 0))
        if i == 0 and r > 0:
            n = max(n, 1)  # progress guarantee: prefill can never livelock
        allow.append(n)
        left -= n
    return allow
