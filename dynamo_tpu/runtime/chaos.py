"""Composition chaos plane: seeded fault schedules, cluster invariants,
replay/shrink (docs/chaos.md).

PR10-18 each ship a hand-written chaos test for ONE failure defense (or one
chosen pair). The combinatorial space where production actually fails — a
straggler convicted mid-migration during a bus outage, a quarantine latch
racing a rolling restart — is what this module executes:

- :class:`ChaosSchedule` draws a timeline of disruptions over the existing
  fault vocabulary from ONE seed (worker kill/restart, ``slow``,
  ``corrupt``, ``poison``, ``delay``, ``migrate_stall``, control-plane
  blackout, drain/undrain, quarantine/unquarantine) under composition
  constraints (at least one worker stays serving at every instant, at most
  one blackout, no kill inside a blackout, every durative action releases
  before the horizon). Serialization is canonical — the same seed emits
  byte-identical JSON forever, which is what makes ``--replay`` a contract
  rather than a hope.
- :class:`ChaosRunner` stands up an N-worker mini-cluster (real tiny
  engines or the deterministic token-mock fallback) under 2x streaming
  load, applies the schedule through :mod:`dynamo_tpu.runtime.faults`, and
  hands the aftermath to the :class:`InvariantSuite`.
- :class:`InvariantSuite` checks safety (delivered bytes equal the
  undisturbed control or end in a typed in-band error; no migration
  completes while a quarantine latch is held), liveness (no stream stuck
  past its deadline; the fleet reconverges within a bound after the last
  fault), and conservation (allocator pages balance, no staged-migration
  leaks, the client's journal ledger matches its stats ledger exactly —
  the equations live in docs/chaos.md).
- a violating run dumps ``schedule.json`` (replayable byte-identically via
  ``tools/chaos.py --replay``) + ``result.json`` + the flight recorder's
  pinned traces; :func:`shrink_schedule` greedily minimizes a violating
  schedule while the violation persists.

Activation: the serving-path hook (:func:`note_event`) is armed only when
``DYN_TPU_CHAOS=1`` — with the knob unset no chaos object is ever
constructed on any serving path (the PR13/PR14/PR18 monkeypatched-ctor
guard), and callers reach it via ``sys.modules.get`` so this module is not
even imported by serving code.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from dynamo_tpu.runtime.envknobs import (
    env_clamped_float,
    env_clamped_int,
    env_flag,
    env_nonneg_int,
    env_raw,
)

logger = logging.getLogger(__name__)

SCHEDULE_VERSION = 1

# the full disruption vocabulary a schedule draws from; every kind maps
# onto an existing runtime/faults.py action or control verb — the chaos
# plane composes defenses, it does not invent new failure physics
KINDS = (
    "kill",           # ungraceful worker death + timed restart
    "slow",           # fail-slow dispatch delay on one worker (timed)
    "corrupt",        # one-shot KV page bit-flip on the transfer plane
    "poison",         # one-shot NaN'd logits lane (output watchdog leg)
    "delay",          # transient rpc frame delays
    "migrate_stall",  # park one in-flight page ship until release
    "blackout",       # statestore+bus down (timed)
    "drain",          # drain/undrain one worker (timed)
    "quarantine",     # integrity latch/clear (timed)
)

# kinds that take a worker out of serving rotation: the generator keeps at
# least one worker free of these at every instant (liveness would be
# vacuous otherwise — a fleet with nobody serving reconverges to nothing)
DISABLING = ("kill", "drain", "quarantine")

# per-kind duration draw bounds (seconds); 0 = instantaneous one-shot
_DURATIONS: Dict[str, Tuple[float, float]] = {
    "kill": (0.3, 1.0),
    "slow": (0.5, 1.5),
    "corrupt": (0.0, 0.0),
    "poison": (0.0, 0.0),
    "delay": (0.0, 0.0),
    "migrate_stall": (0.3, 0.8),
    "blackout": (0.4, 1.0),
    "drain": (0.5, 1.5),
    "quarantine": (0.5, 1.5),
}

DEFAULT_WEIGHTS: Dict[str, float] = {
    "kill": 2.0,
    "slow": 2.0,
    "corrupt": 2.0,
    "poison": 1.0,
    "delay": 2.0,
    "migrate_stall": 1.0,
    "blackout": 1.0,
    "drain": 3.0,
    "quarantine": 1.0,
}

# drain source the runner uses so its undrain never clears an operator's
# (or the straggler plane's) independent drain order
CHAOS_DRAIN_SOURCE = "chaos"
CHAOS_QUARANTINE_SOURCE = "chaos"

# observer timeline bound: a soak run emits thousands of events; the
# invariant checks only need the recent window (PR8 decision-ring pattern)
CHAOS_LOG_MAX = 4096

# grace at a quarantine window's leading edge: a ship whose frame cleared
# the receiver's latch check a scheduling beat before the latch landed may
# legitimately note its completion just after (docs/chaos.md §Invariants)
QUARANTINE_EDGE_GRACE = 0.05


# =========================================================================
# policy knobs (PR3 clamping contract via envknobs)
# =========================================================================


@dataclass(frozen=True)
class ChaosPolicy:
    """Knob bundle for env-driven chaos runs (``tools/chaos.py`` and the
    soak leg). ``enabled`` gates the serving-path observer hook; the rest
    parameterize schedule generation."""

    enabled: bool = False
    seed: int = 0
    duration: float = 8.0
    max_events: int = 12
    weights: Dict[str, float] = field(default_factory=lambda: dict(DEFAULT_WEIGHTS))

    @classmethod
    def from_env(cls) -> "ChaosPolicy":
        d = cls()
        return cls(
            enabled=env_flag("DYN_TPU_CHAOS", d.enabled),
            seed=env_nonneg_int("DYN_TPU_CHAOS_SEED", d.seed),
            duration=env_clamped_float(
                "DYN_TPU_CHAOS_DURATION", d.duration, 1.0, 3600.0
            ),
            max_events=env_clamped_int(
                "DYN_TPU_CHAOS_EVENTS", d.max_events, 1, 500
            ),
            weights=_parse_weights(env_raw("DYN_TPU_CHAOS_WEIGHTS")),
        )


def _parse_weights(raw: Optional[str]) -> Dict[str, float]:
    """``DYN_TPU_CHAOS_WEIGHTS`` is a JSON object kind→weight; malformed
    input, unknown kinds, and negative weights degrade to the defaults /
    are dropped / clamp to 0 — never to a surprise schedule."""
    weights = dict(DEFAULT_WEIGHTS)
    if not raw:
        return weights
    try:
        parsed = json.loads(raw)
        if not isinstance(parsed, dict):
            raise ValueError("weights must be a JSON object")
    except (ValueError, TypeError):
        logger.warning("malformed DYN_TPU_CHAOS_WEIGHTS ignored: %r", raw)
        return weights
    for kind, w in parsed.items():
        if kind not in KINDS:
            logger.warning("unknown chaos kind %r in weights ignored", kind)
            continue
        try:
            weights[kind] = max(float(w), 0.0)
        except (TypeError, ValueError):
            logger.warning("non-numeric weight for %r ignored", kind)
    return weights


def maybe_from_env() -> Optional[ChaosPolicy]:
    """The zero-overhead gate: None unless ``DYN_TPU_CHAOS=1`` — serving
    paths behind this never construct a chaos object."""
    if not env_flag("DYN_TPU_CHAOS", False):
        return None
    return ChaosPolicy.from_env()


# =========================================================================
# schedule: one seed → one timeline, canonically serialized
# =========================================================================


@dataclass(frozen=True)
class ChaosEvent:
    """One disruption. ``t`` is seconds from load start; durative kinds
    hold until ``t + duration`` (restart, un-slow, blackout end, undrain,
    unquarantine, stall release); ``worker`` indexes the mini-cluster
    (ignored by ``blackout``, which takes out the control plane fleetwide).
    """

    t: float
    kind: str
    worker: int = 0
    duration: float = 0.0

    def to_dict(self) -> dict:
        return {
            "t": self.t, "kind": self.kind, "worker": self.worker,
            "duration": self.duration,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ChaosEvent":
        kind = str(d["kind"])
        if kind not in KINDS:
            raise ValueError(f"unknown chaos kind {kind!r}")
        return cls(
            t=float(d["t"]), kind=kind, worker=int(d.get("worker", 0)),
            duration=float(d.get("duration", 0.0)),
        )

    def end(self) -> float:
        return self.t + self.duration


@dataclass(frozen=True)
class ChaosSchedule:
    """A seeded timeline of :class:`ChaosEvent`, sorted by ``t``.

    :meth:`generate` is a pure function of its arguments — no wall clock,
    no global RNG — so the same seed yields the same schedule on any host,
    and :meth:`to_json` is canonical (sorted keys, fixed separators,
    4-decimal times fixed at generation) so two runs of
    ``tools/chaos.py run --seed N`` emit byte-identical files.
    """

    seed: int
    n_workers: int
    horizon: float
    events: Tuple[ChaosEvent, ...]

    # -- generation --------------------------------------------------------

    @classmethod
    def generate(
        cls,
        seed: int,
        n_workers: int = 3,
        horizon: float = 8.0,
        max_events: int = 12,
        weights: Optional[Dict[str, float]] = None,
    ) -> "ChaosSchedule":
        if n_workers < 2:
            raise ValueError("chaos needs >= 2 workers (failover must have "
                             "somewhere to go)")
        rng = random.Random(seed)
        weights = {
            k: max(float((weights or DEFAULT_WEIGHTS).get(k, 0.0)), 0.0)
            for k in KINDS
        }
        kinds = [k for k in KINDS if weights[k] > 0.0]
        if not kinds:
            raise ValueError("all chaos weights are zero")
        wlist = [weights[k] for k in kinds]
        target = 1 + rng.randrange(max_events)
        accepted: List[ChaosEvent] = []
        # rejection sampling under the composition constraints: bounded
        # tries keep generation total even for over-constrained draws
        for _ in range(max_events * 40):
            if len(accepted) >= target:
                break
            kind = rng.choices(kinds, weights=wlist)[0]
            lo, hi = _DURATIONS[kind]
            duration = round(rng.uniform(lo, hi), 4) if hi > 0 else 0.0
            latest = horizon * 0.85 - duration
            if latest <= 0.2:
                continue
            t = round(rng.uniform(0.2, latest), 4)
            ev = ChaosEvent(
                t=t, kind=kind, worker=rng.randrange(n_workers),
                duration=duration,
            )
            if _admissible(ev, accepted, n_workers):
                accepted.append(ev)
        events = tuple(sorted(accepted, key=lambda e: (e.t, e.kind, e.worker)))
        return cls(seed=seed, n_workers=n_workers,
                   horizon=round(float(horizon), 4), events=events)

    def replace_events(self, events) -> "ChaosSchedule":
        return ChaosSchedule(
            seed=self.seed, n_workers=self.n_workers, horizon=self.horizon,
            events=tuple(events),
        )

    # -- canonical serialization ------------------------------------------

    def to_json(self) -> str:
        return json.dumps(
            {
                "version": SCHEDULE_VERSION,
                "seed": self.seed,
                "n_workers": self.n_workers,
                "horizon": self.horizon,
                "events": [e.to_dict() for e in self.events],
            },
            sort_keys=True, separators=(",", ":"),
        )

    @classmethod
    def from_json(cls, text: str) -> "ChaosSchedule":
        d = json.loads(text)
        if d.get("version") != SCHEDULE_VERSION:
            raise ValueError(
                f"unsupported schedule version {d.get('version')!r}"
            )
        return cls(
            seed=int(d["seed"]), n_workers=int(d["n_workers"]),
            horizon=float(d["horizon"]),
            events=tuple(ChaosEvent.from_dict(e) for e in d["events"]),
        )


def _overlaps(a0: float, a1: float, b0: float, b1: float) -> bool:
    return a0 < b1 and b0 < a1


def _admissible(ev: ChaosEvent, accepted: List[ChaosEvent],
                n_workers: int) -> bool:
    """The composition constraints (docs/chaos.md §Schedule grammar):

    - at every instant at least one worker is free of kill/drain/
      quarantine (someone must be able to absorb migrations/failovers);
    - a worker carries at most one disabling action at a time (a drain
      order against a dead process is noise, not composition);
    - at most one blackout at a time, and no kill overlapping a blackout
      (a restarted worker re-registers through the statestore — with the
      store dark the restart cannot complete within the liveness bound).
    """
    if ev.kind == "blackout":
        for o in accepted:
            if o.kind == "blackout" and _overlaps(
                ev.t, ev.end(), o.t, o.end()
            ):
                return False
            if o.kind == "kill" and _overlaps(ev.t, ev.end(), o.t, o.end()):
                return False
        return True
    if ev.kind == "kill":
        for o in accepted:
            if o.kind == "blackout" and _overlaps(
                ev.t, ev.end(), o.t, o.end()
            ):
                return False
    if ev.kind in DISABLING:
        disabled = set()
        for o in accepted:
            if o.kind in DISABLING and _overlaps(
                ev.t, ev.end(), o.t, o.end()
            ):
                if o.worker == ev.worker:
                    return False
                disabled.add(o.worker)
        if len(disabled) + 1 >= n_workers:
            return False
    return True


# =========================================================================
# shrink: greedy 1-minimal reduction of a violating schedule
# =========================================================================


def shrink_schedule(
    schedule: ChaosSchedule,
    check: Callable[[ChaosSchedule], bool],
    log: Optional[Callable[[str], None]] = None,
) -> ChaosSchedule:
    """Greedily drop events while ``check`` (True = still violating) holds:
    repeatedly try removing each event; keep any removal that preserves the
    violation; stop at a 1-minimal schedule (removing any single remaining
    event loses the violation). Event count decreases monotonically; the
    result is strictly smaller whenever any event was removable."""
    if not check(schedule):
        raise ValueError("schedule does not violate; nothing to shrink")
    events = list(schedule.events)
    changed = True
    while changed and len(events) > 1:
        changed = False
        i = 0
        while i < len(events) and len(events) > 1:
            candidate = schedule.replace_events(
                events[:i] + events[i + 1:]
            )
            if check(candidate):
                dropped = events.pop(i)
                changed = True
                if log:
                    log(f"shrink: dropped t={dropped.t} {dropped.kind} "
                        f"w{dropped.worker} ({len(events)} left)")
            else:
                i += 1
    return schedule.replace_events(events)


# =========================================================================
# observer: the serving-path hook (constructor-free when the knob is off)
# =========================================================================


class ChaosObserver:
    """Bounded process-global event recorder the invariant suite reads:
    migration completions, drain flips, and quarantine latches land here
    via :func:`note_event` (fed by lazy ``sys.modules.get`` hooks in
    migration/distributed/integrity — no serving module imports chaos).
    Thread-safe: engine threads note migrations, the loop notes drains."""

    def __init__(self, maxlen: int = CHAOS_LOG_MAX):
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=maxlen)

    def note(self, kind: str, fields: Dict[str, Any]) -> None:
        with self._lock:
            self._events.append((time.monotonic(), kind, dict(fields)))

    def events(self, kind: Optional[str] = None) -> List[tuple]:
        with self._lock:
            out = list(self._events)
        if kind is not None:
            out = [e for e in out if e[1] == kind]
        return out

    def clear(self) -> None:
        with self._lock:
            self._events.clear()


_observer: Optional[ChaosObserver] = None
_env_checked = False
_OBSERVER_LOCK = threading.Lock()


def note_event(kind: str, **fields: Any) -> None:
    """Serving-path hook: record one event into the process observer.

    Zero-overhead contract: with ``DYN_TPU_CHAOS`` unset this is one
    None-check after a once-only env probe — no object is constructed
    (the monkeypatched-ctor guard in tests/test_chaos_plane.py proves it).
    """
    obs = _observer
    if obs is None:
        if _env_checked:
            return
        obs = _arm_from_env()
        if obs is None:
            return
    obs.note(kind, fields)


def _arm_from_env() -> Optional[ChaosObserver]:
    global _observer, _env_checked
    with _OBSERVER_LOCK:
        if _observer is not None:
            return _observer
        if _env_checked:
            return None
        _env_checked = True
        if maybe_from_env() is None:
            return None
        _observer = ChaosObserver()
        logger.warning("chaos observer ARMED from DYN_TPU_CHAOS")
        return _observer


def observer() -> Optional[ChaosObserver]:
    return _observer


def install_observer(obs: Optional[ChaosObserver]) -> None:
    """Explicit arm (the ChaosRunner, tests); env state is not consulted
    again until :func:`reset_for_tests`."""
    global _observer, _env_checked
    with _OBSERVER_LOCK:
        _observer = obs
        _env_checked = True


def reset_for_tests() -> None:
    """Drop the process observer and the once-only env probe (conftest
    autouse reset: one test's chaos events must not bleed into another's
    invariant or zero-overhead assertions)."""
    global _observer, _env_checked
    with _OBSERVER_LOCK:
        _observer = None
        _env_checked = False


# =========================================================================
# invariants
# =========================================================================


INVARIANTS = (
    "safety.bytes",
    "safety.typed_errors",
    "safety.quarantine_no_ship",
    "liveness.streams",
    "liveness.reconverge",
    "conservation.pages",
    "conservation.staged",
    "conservation.disruptions",
)


@dataclass(frozen=True)
class Violation:
    invariant: str
    detail: str

    def to_dict(self) -> dict:
        return {"invariant": self.invariant, "detail": self.detail}


@dataclass
class StreamResult:
    index: int
    prompt: List[int]
    golden: List[int]
    toks: List[int] = field(default_factory=list)
    errs: List[str] = field(default_factory=list)
    done: bool = False
    journal_migrations: int = 0
    journal_resumes: int = 0


@dataclass
class ChaosContext:
    """Everything the invariant suite judges — assembled by the runner,
    constructible by hand in unit tests (injected-violation coverage)."""

    streams: List[StreamResult] = field(default_factory=list)
    engine_snapshots: List[Dict[str, Any]] = field(default_factory=list)
    live_requests: List[int] = field(default_factory=list)
    client_stats: Dict[str, int] = field(default_factory=dict)
    migration_counters: Tuple[int, int, int] = (0, 0, 0)
    # [(start, end)] monotonic quarantine windows + migration completion
    # timestamps (monotonic, ok-only) from the observer
    quarantine_windows: List[Tuple[float, float]] = field(default_factory=list)
    migration_times: List[float] = field(default_factory=list)
    reconverged: bool = True
    reconverge_detail: str = ""
    stuck_streams: List[int] = field(default_factory=list)


class InvariantSuite:
    """The standing cluster invariants (docs/chaos.md §Invariant catalog).
    :meth:`evaluate` returns every violation; :meth:`table` additionally
    reports per-invariant pass/fail for the llmctl rendering."""

    def evaluate(self, ctx: ChaosContext) -> List[Violation]:
        return [v for vs in self.table(ctx).values() for v in vs]

    def table(self, ctx: ChaosContext) -> Dict[str, List[Violation]]:
        out: Dict[str, List[Violation]] = {name: [] for name in INVARIANTS}

        # -- safety: every delivered byte is either equal to the
        # undisturbed control or precedes a typed in-band error ------------
        for s in ctx.streams:
            if s.errs:
                # typed in-band failure: the bytes delivered BEFORE it must
                # still be a control prefix (no wrong bytes, ever)
                if s.toks != s.golden[: len(s.toks)]:
                    out["safety.bytes"].append(Violation(
                        "safety.bytes",
                        f"stream {s.index}: delivered bytes before typed "
                        f"error diverge from control at token "
                        f"{_first_divergence(s.toks, s.golden)}",
                    ))
                continue
            if s.done and s.toks != s.golden:
                out["safety.bytes"].append(Violation(
                    "safety.bytes",
                    f"stream {s.index}: wrong bytes — diverges from "
                    f"control at token {_first_divergence(s.toks, s.golden)}"
                    f" ({len(s.toks)}/{len(s.golden)} delivered)",
                ))
            if not s.done and not s.errs and s.index not in ctx.stuck_streams:
                out["safety.typed_errors"].append(Violation(
                    "safety.typed_errors",
                    f"stream {s.index}: ended incomplete with neither a "
                    f"finish nor a typed in-band error",
                ))

        # -- safety: quarantined processes never donate pages --------------
        # (single-process harness note: the latch is process-global, so
        # this degrades to "no migration completes while ANY quarantine is
        # latched" — documented in docs/chaos.md)
        for t in ctx.migration_times:
            for (q0, q1) in ctx.quarantine_windows:
                if q0 + QUARANTINE_EDGE_GRACE <= t <= q1:
                    out["safety.quarantine_no_ship"].append(Violation(
                        "safety.quarantine_no_ship",
                        f"migration completed at t={t:.3f} inside "
                        f"quarantine window [{q0:.3f}, {q1:.3f}] — "
                        f"untrusted pages were donated",
                    ))

        # -- liveness ------------------------------------------------------
        for i in ctx.stuck_streams:
            out["liveness.streams"].append(Violation(
                "liveness.streams",
                f"stream {i}: stuck past the reaper+deadline bound",
            ))
        if not ctx.reconverged:
            out["liveness.reconverge"].append(Violation(
                "liveness.reconverge",
                ctx.reconverge_detail or "fleet did not reconverge within "
                "the bound after the last fault",
            ))

        # -- conservation --------------------------------------------------
        for w, snap in enumerate(ctx.engine_snapshots):
            blocks = snap.get("kv_active_blocks")
            if blocks:
                out["conservation.pages"].append(Violation(
                    "conservation.pages",
                    f"worker {w}: {blocks} KV blocks still allocated after "
                    f"the fleet settled (leak or unfreed stream)",
                ))
            staged = snap.get("migrate_staged")
            if staged:
                out["conservation.staged"].append(Violation(
                    "conservation.staged",
                    f"worker {w}: {staged} staged migration(s) leaked past "
                    f"settle (TTL sweep or abort failed to free them)",
                ))
        for w, live in enumerate(ctx.live_requests):
            if live:
                out["conservation.pages"].append(Violation(
                    "conservation.pages",
                    f"worker {w}: {live} live request(s) after settle",
                ))

        # ledger equations (exact; docs/chaos.md §Conservation): the
        # client's per-stream journals and its stats counters are two
        # ledgers over the same disruptions and must agree token-for-token
        stats = ctx.client_stats
        if stats:
            j_mig = sum(s.journal_migrations for s in ctx.streams)
            j_res = sum(s.journal_resumes for s in ctx.streams)
            c_mig = stats.get("migrations", 0) + stats.get(
                "migration_resumes", 0
            )
            c_res = stats.get("resumes", 0)
            if j_mig != c_mig:
                out["conservation.disruptions"].append(Violation(
                    "conservation.disruptions",
                    f"journal migrations {j_mig} != client "
                    f"migrations+migration_resumes {c_mig}",
                ))
            if j_res != c_res:
                out["conservation.disruptions"].append(Violation(
                    "conservation.disruptions",
                    f"journal resumes {j_res} != client resumes {c_res}",
                ))
            m_ok = ctx.migration_counters[0]
            if m_ok < stats.get("migrations", 0):
                out["conservation.disruptions"].append(Violation(
                    "conservation.disruptions",
                    f"client followed {stats.get('migrations', 0)} "
                    f"migrations but coordinators shipped only {m_ok}",
                ))
        return out


def _first_divergence(got: List[int], want: List[int]) -> int:
    for i, (a, b) in enumerate(zip(got, want)):
        if a != b:
            return i
    return min(len(got), len(want))


# =========================================================================
# report
# =========================================================================


@dataclass
class ChaosReport:
    schedule: ChaosSchedule
    violations: List[Violation]
    invariants: Dict[str, bool]          # name → passed
    stats: Dict[str, Any]
    decision_log: List[dict]
    traces: List[dict] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "seed": self.schedule.seed,
            "violations": [v.to_dict() for v in self.violations],
            "invariants": dict(self.invariants),
            "stats": dict(self.stats),
            "decision_log": list(self.decision_log),
        }

    def write(self, run_dir: str) -> None:
        """Dump the replay artifact set: ``schedule.json`` (canonical —
        feed it to ``tools/chaos.py --replay``), ``result.json``, and the
        flight recorder's pinned traces as ``traces.jsonl``."""
        import os

        os.makedirs(run_dir, exist_ok=True)
        with open(os.path.join(run_dir, "schedule.json"), "w") as f:
            f.write(self.schedule.to_json())
        with open(os.path.join(run_dir, "result.json"), "w") as f:
            f.write(json.dumps(self.to_dict(), sort_keys=True, indent=2))
        if self.traces:
            with open(os.path.join(run_dir, "traces.jsonl"), "w") as f:
                for t in self.traces:
                    f.write(json.dumps(t, sort_keys=True) + "\n")


# =========================================================================
# the runner
# =========================================================================


def _next_token(toks: List[int]) -> int:
    """Pure function of the full context — the greedy-decode stand-in for
    the mock fleet (the tests/test_resume.py idiom): any two workers
    continue an identical prefix identically, so resumed output byte-
    compares against an undisturbed control."""
    return (toks[-1] * 31 + len(toks) * 7 + 13) % 50021


def mock_expected_stream(prompt: List[int], max_tokens: int) -> List[int]:
    toks = list(prompt)
    out = []
    for _ in range(max_tokens):
        nxt = _next_token(toks)
        toks.append(nxt)
        out.append(nxt)
    return out


class MockChaosWorker:
    """Deterministic token mock for the no-accelerator fallback: supports
    the kill / delay / blackout / drain legs (no dispatch or pages planes,
    so slow/corrupt/poison/migrate_stall compositions need real engines).
    Duck-types the engine surface the runner's conservation checks read."""

    def __init__(self, tag: str, delay: float = 0.01):
        self.tag = tag
        self.delay = delay
        self._live = 0
        self._fault_addr = "engine"  # serve() rewrites to the worker id

    async def generate(self, request):
        from dynamo_tpu.runtime.annotated import Annotated

        req = request.data
        toks = list(req["token_ids"])
        max_t = int(req["stop_conditions"]["max_tokens"])
        self._live += 1
        try:
            for _ in range(max_t):
                if request.context.is_stopped:
                    return
                nxt = _next_token(toks)
                toks.append(nxt)
                yield Annotated.from_data({"token_ids": [nxt]})
                await asyncio.sleep(self.delay)
            yield Annotated.from_data(
                {"token_ids": [], "finish_reason": "length"}
            )
        finally:
            self._live -= 1

    def live_request_count(self) -> int:
        return self._live

    def metrics_snapshot(self) -> Dict[str, Any]:
        return {"kv_active_blocks": 0, "migrate_staged": 0}

    def close(self) -> None:
        pass


class ChaosRunner:
    """Stand up an N-worker mini-cluster, drive 2x streaming load, apply a
    :class:`ChaosSchedule` through :mod:`runtime.faults` and the control
    verbs, then judge the aftermath with the :class:`InvariantSuite`.

    ``engine_factory(i)`` builds worker ``i``'s engine (real tiny engines
    in the gate; None → the :class:`MockChaosWorker` fallback). Pass
    ``engines`` to reuse pre-built engines across runs (the pairwise smoke
    shares three tiny engines over its whole matrix) — reused engines are
    not closed on exit.
    """

    def __init__(
        self,
        schedule: ChaosSchedule,
        engine_factory: Optional[Callable[[int], Any]] = None,
        engines: Optional[List[Any]] = None,
        policy: Optional[Any] = None,   # ResiliencePolicy
        streams_per_worker: int = 2,
        prompt_len: int = 16,
        max_tokens: int = 20,
        stream_deadline: float = 60.0,
        reconverge_bound: float = 20.0,
        settle_bound: float = 15.0,
        namespace: str = "chaos",
    ):
        self.schedule = schedule
        self.engine_factory = engine_factory
        self._shared_engines = engines
        self.policy = policy
        self.streams_per_worker = streams_per_worker
        self.prompt_len = prompt_len
        self.max_tokens = max_tokens
        self.stream_deadline = stream_deadline
        self.reconverge_bound = reconverge_bound
        self.settle_bound = settle_bound
        self.namespace = namespace
        self.mock = engine_factory is None and engines is None

    # -- cluster plumbing --------------------------------------------------

    def _payload(self, prompt: List[int]) -> dict:
        return {
            "token_ids": list(prompt),
            "stop_conditions": {
                "max_tokens": self.max_tokens, "ignore_eos": True,
            },
            "sampling_options": {"temperature": 0.0},
        }

    def _prompt(self, i: int) -> List[int]:
        return list(range(3 + i, 3 + i + self.prompt_len))

    def _default_policy(self):
        from dynamo_tpu.runtime.resilience import ResiliencePolicy

        return ResiliencePolicy(
            request_timeout=self.stream_deadline,
            connect_timeout=2.0,
            max_attempts=6,
            backoff_base=0.01,
            backoff_max=0.05,
            breaker_threshold=3,
            breaker_cooldown=2.0,
            resume_attempts=4,
            seed=self.schedule.seed,
        )

    async def _build_engine(self, i: int):
        if self._shared_engines is not None:
            return self._shared_engines[i]
        if self.engine_factory is not None:
            return await asyncio.to_thread(self.engine_factory, i)
        # pace the mock so the load actually spans the schedule horizon —
        # otherwise every stream finishes before the first fault lands and
        # the run exercises nothing
        delay = max(self.schedule.horizon * 0.7 / self.max_tokens, 0.005)
        return MockChaosWorker(f"w{i}", delay=delay)

    async def _golden(self, engine, prompt: List[int]) -> List[int]:
        if self.mock:
            return mock_expected_stream(prompt, self.max_tokens)
        from dynamo_tpu.runtime.engine import Context

        out: List[int] = []
        async for item in engine.generate(Context(self._payload(prompt))):
            if item.is_error:
                raise RuntimeError(
                    f"control stream errored: {item.error_message()}"
                )
            out.extend((item.data or {}).get("token_ids", []))
        return out

    async def _serve_worker(self, i: int, ss_url: str):
        from dynamo_tpu.disagg.migration import attach_migration
        from dynamo_tpu.runtime.distributed import DistributedRuntime

        rt = await DistributedRuntime.create(ss_url, "127.0.0.1:1")
        ep = rt.namespace(self.namespace).component("w").endpoint("generate")
        await ep.serve(self._engines[i])
        coord = None
        if not self.mock:
            coord = await attach_migration(ep, self._engines[i])
        return rt, coord

    # -- event application -------------------------------------------------

    async def _apply_start(self, ev: ChaosEvent, inj) -> None:
        from dynamo_tpu.runtime import integrity
        from dynamo_tpu.runtime.faults import FaultRule

        w = ev.worker % len(self._engines)
        if ev.kind == "kill":
            rt = self._rts[w]
            self._rts[w] = None
            with contextlib.suppress(Exception):
                await rt._rpc_server.stop(drain_timeout=0.05)
            with contextlib.suppress(Exception):
                await rt.shutdown()
        elif ev.kind == "blackout":
            inj.begin_blackout()
        elif ev.kind == "drain":
            if self._rts[w] is not None:
                self._rts[w].set_draining(True, source=CHAOS_DRAIN_SOURCE)
        elif ev.kind == "quarantine":
            t0 = time.monotonic()
            integrity.tracker().quarantine(
                source=CHAOS_QUARANTINE_SOURCE,
                reason=f"chaos schedule seed={self.schedule.seed}",
            )
            self._quarantine_open = t0
        elif ev.kind == "slow":
            rule = FaultRule(
                plane="engine", point="dispatch", action="slow",
                match_addr=self._addr_of(w), delay=0.03, jitter=0.03,
            )
            self._timed_rules[id(ev)] = rule
            inj.add_rule(rule)
        elif ev.kind == "corrupt":
            inj.add_rule(FaultRule(
                plane="transfer", point="pages", action="corrupt",
                max_fires=1,
            ))
        elif ev.kind == "poison":
            inj.add_rule(FaultRule(
                plane="engine", point="dispatch", action="poison",
                match_addr=self._addr_of(w), max_fires=1,
            ))
        elif ev.kind == "delay":
            inj.add_rule(FaultRule(
                plane="rpc", point="read", action="delay", delay=0.05,
                max_fires=3,
            ))
        elif ev.kind == "migrate_stall":
            inj.add_rule(FaultRule(
                plane="transfer", point="migrate", action="migrate_stall",
                max_fires=1,
            ))

    async def _apply_end(self, ev: ChaosEvent, inj) -> None:
        from dynamo_tpu.runtime import integrity

        w = ev.worker % len(self._engines)
        if ev.kind == "kill":
            rt, coord = await self._serve_worker(w, self._ss.url)
            self._rts[w] = rt
            self._coords[w] = coord
        elif ev.kind == "blackout":
            inj.end_blackout()
        elif ev.kind == "drain":
            if self._rts[w] is not None:
                self._rts[w].set_draining(False, source=CHAOS_DRAIN_SOURCE)
        elif ev.kind == "quarantine":
            integrity.clear_quarantine(CHAOS_QUARANTINE_SOURCE)
            if self._quarantine_open is not None:
                self._quarantine_windows.append(
                    (self._quarantine_open, time.monotonic())
                )
                self._quarantine_open = None
        elif ev.kind == "slow":
            rule = self._timed_rules.pop(id(ev), None)
            if rule is not None:
                inj.remove_rule(rule)
        elif ev.kind == "migrate_stall":
            inj.release_stalls()

    def _addr_of(self, w: int) -> Optional[str]:
        # serve() rewrites engine._fault_addr from the "engine" sentinel to
        # the worker id, which is what dispatch-point rules match on
        addr = getattr(self._engines[w], "_fault_addr", None)
        return addr if addr not in (None, "engine") else None

    # -- the run -----------------------------------------------------------

    async def run(self) -> ChaosReport:
        from dynamo_tpu.runtime import faults, integrity, tracing
        from dynamo_tpu.disagg import migration as mig_mod
        from dynamo_tpu.runtime.distributed import DistributedRuntime
        from dynamo_tpu.runtime.engine import Context
        from dynamo_tpu.runtime.faults import FaultInjector
        from dynamo_tpu.runtime.statestore import StateStoreServer

        if faults.current() is not None:
            raise RuntimeError("a fault injector is already installed")
        sched = self.schedule
        n = sched.n_workers
        self._timed_rules: Dict[int, Any] = {}
        self._quarantine_windows: List[Tuple[float, float]] = []
        self._quarantine_open: Optional[float] = None

        mig_base = mig_mod.migration_counters()
        prev_observer = observer()
        obs = ChaosObserver()
        install_observer(obs)

        self._engines = [await self._build_engine(i) for i in range(n)]
        n_streams = self.streams_per_worker * n
        prompts = [self._prompt(i) for i in range(n_streams)]
        goldens = [
            await self._golden(self._engines[0], p) for p in prompts
        ]

        self._ss = StateStoreServer(port=0)
        await self._ss.start()
        self._rts: List[Any] = []
        self._coords: List[Any] = []
        fe = client = None
        inj = FaultInjector(seed=sched.seed)
        stuck: List[int] = []
        reconverged, reconverge_detail = True, ""
        try:
            for i in range(n):
                rt, coord = await self._serve_worker(i, self._ss.url)
                self._rts.append(rt)
                self._coords.append(coord)
            fe = await DistributedRuntime.create(
                self._ss.url, "127.0.0.1:1"
            )
            client = await fe.namespace(self.namespace).component(
                "w"
            ).endpoint("generate").client(
                "round_robin", policy=self.policy or self._default_policy()
            )
            await client.wait_for_instances(n, timeout=10)

            faults.install(inj)

            results = [
                StreamResult(index=i, prompt=prompts[i], golden=goldens[i])
                for i in range(n_streams)
            ]

            async def one(i: int) -> None:
                s = results[i]
                ctx = Context(self._payload(s.prompt))
                async for item in client.generate(ctx):
                    if item.is_error:
                        s.errs.append(item.error_message() or "error")
                    elif isinstance(item.data, dict):
                        s.toks.extend(item.data.get("token_ids", []))
                s.done = True
                j = ctx.context.journal
                if j is not None:
                    s.journal_migrations = j.migrations
                    s.journal_resumes = j.resumes

            loop = asyncio.get_running_loop()
            t0 = loop.time()
            tasks = [asyncio.create_task(one(i)) for i in range(n_streams)]

            # unified timeline: starts and ends of every event, in order
            timeline: List[Tuple[float, str, ChaosEvent]] = []
            for ev in sched.events:
                timeline.append((ev.t, "start", ev))
                if ev.duration > 0:
                    timeline.append((ev.end(), "end", ev))
            timeline.sort(key=lambda x: (x[0], x[1] == "start"))
            for when, phase, ev in timeline:
                delay = t0 + when - loop.time()
                if delay > 0:
                    await asyncio.sleep(delay)
                logger.info("chaos %s %s w%d (t=%.2f)", phase, ev.kind,
                            ev.worker, when)
                if phase == "start":
                    await self._apply_start(ev, inj)
                else:
                    await self._apply_end(ev, inj)

            # wait the load out under the liveness bound
            done, pending = await asyncio.wait(
                tasks, timeout=self.stream_deadline
            )
            for i, task in enumerate(tasks):
                if task in pending:
                    stuck.append(i)
                    task.cancel()
            if pending:
                await asyncio.gather(*pending, return_exceptions=True)
            for task in done:
                exc = task.exception()
                if exc is not None:
                    raise exc

            # release everything the schedule may have left holding
            inj.clear_rules()
            inj.end_blackout()
            faults.uninstall()
            for w, rt in enumerate(self._rts):
                if rt is None:  # killed with no restart left in-schedule
                    rt, coord = await self._serve_worker(w, self._ss.url)
                    self._rts[w] = rt
                    self._coords[w] = coord
                rt.set_draining(False, source=CHAOS_DRAIN_SOURCE)
            integrity.clear_quarantine(CHAOS_QUARANTINE_SOURCE)
            if self._quarantine_open is not None:
                self._quarantine_windows.append(
                    (self._quarantine_open, time.monotonic())
                )
                self._quarantine_open = None

            # liveness: the fleet reconverges — full discovery, and a fresh
            # probe stream completes byte-equal within the bound. The probe
            # RETRIES inside the bound: right after an undrain the store
            # can still serve stale draining/unhealthy instance records
            # (the re-put rides the next load-report beat), and a breaker
            # opened by the schedule needs its cooldown — both are the
            # fleet converging, not failing to
            deadline = loop.time() + self.reconverge_bound
            reconverged, reconverge_detail = False, ""
            try:
                await client.wait_for_instances(
                    n, timeout=self.reconverge_bound
                )
            except asyncio.TimeoutError:
                reconverge_detail = (
                    f"discovery never re-listed all {n} workers within "
                    f"{self.reconverge_bound}s of the last fault"
                )
            else:
                while True:
                    probe = StreamResult(
                        index=-1, prompt=prompts[0], golden=goldens[0]
                    )
                    p_ctx = Context(self._payload(probe.prompt))
                    try:
                        async def _probe():
                            async for item in client.generate(p_ctx):
                                if item.is_error:
                                    probe.errs.append(
                                        item.error_message() or "err"
                                    )
                                elif isinstance(item.data, dict):
                                    probe.toks.extend(
                                        item.data.get("token_ids", [])
                                    )
                        await asyncio.wait_for(
                            _probe(), max(deadline - loop.time(), 0.1)
                        )
                    except asyncio.TimeoutError:
                        reconverge_detail = "post-fault probe timed out"
                        break
                    except Exception as e:  # NoHealthyInstances et al.
                        logger.info(
                            "chaos reconverge probe failed (retrying "
                            "within the bound): %s: %s",
                            type(e).__name__, e,
                        )
                        probe.errs.append(f"{type(e).__name__}: {e}")
                    if not probe.errs and probe.toks == probe.golden:
                        reconverged = True
                        break
                    if loop.time() >= deadline:
                        reconverge_detail = (
                            f"post-fault probe failing at the bound: "
                            f"errs={probe.errs[:2]}, "
                            f"{len(probe.toks)}/{len(probe.golden)} tokens"
                        )
                        break
                    await asyncio.sleep(0.25)

            # settle: drains/aborts/TTL sweeps must return every page
            await self._settle()

            ctx = ChaosContext(
                streams=results,
                engine_snapshots=[
                    e.metrics_snapshot() for e in self._engines
                ],
                live_requests=[
                    e.live_request_count() for e in self._engines
                ],
                client_stats=dict(client.stats),
                migration_counters=tuple(
                    a - b for a, b in
                    zip(mig_mod.migration_counters(), mig_base)
                ),
                quarantine_windows=list(self._quarantine_windows),
                migration_times=[
                    t for (t, kind, f) in obs.events("migration")
                    if f.get("ok")
                ],
                reconverged=reconverged,
                reconverge_detail=reconverge_detail,
                stuck_streams=stuck,
            )
            suite = InvariantSuite()
            table = suite.table(ctx)
            violations = [v for vs in table.values() for v in vs]
            report = ChaosReport(
                schedule=sched,
                violations=violations,
                invariants={k: not vs for k, vs in table.items()},
                stats={
                    "streams": n_streams,
                    "stuck": len(stuck),
                    "errored": sum(1 for s in results if s.errs),
                    "client": dict(client.stats),
                    "migrations": ctx.migration_counters[0],
                    "migrations_failed": ctx.migration_counters[1],
                    "mock": self.mock,
                },
                decision_log=[
                    {
                        "seq": getattr(d, "seq", 0), "plane": d.plane,
                        "addr": d.addr, "point": d.point,
                        "op_index": d.op_index, "action": d.action,
                        "detail": getattr(d, "detail", ""),
                    }
                    for d in list(inj.log)
                ],
                traces=[
                    t for t in tracing.recorder().traces()
                    if t.get("pinned")
                ] if violations else [],
            )
            return report
        finally:
            faults.uninstall()
            install_observer(prev_observer)
            if client is not None:
                await client.close()
            for rt in self._rts + ([fe] if fe is not None else []):
                if rt is not None:
                    with contextlib.suppress(Exception):
                        await rt.shutdown()
            if self._shared_engines is None:
                for e in self._engines:
                    with contextlib.suppress(Exception):
                        e.close()
            await self._ss.stop()
            integrity.clear_quarantine(CHAOS_QUARANTINE_SOURCE)

    async def _settle(self) -> None:
        """Poll the fleet quiescent: zero live requests, zero allocated KV
        blocks, zero staged migrations on every worker — the conservation
        invariants judge whatever is left at the bound."""
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        while loop.time() - t0 < self.settle_bound:
            busy = False
            for e in self._engines:
                snap = e.metrics_snapshot()
                if (
                    e.live_request_count()
                    or snap.get("kv_active_blocks")
                    or snap.get("migrate_staged")
                ):
                    busy = True
                    break
            if not busy:
                return
            await asyncio.sleep(0.1)
