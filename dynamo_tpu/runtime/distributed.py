"""Distributed runtime: Namespace → Component → Endpoint over the self-hosted
control plane (statestore.py) + event plane (bus.py) + direct RPC (rpc.py).

Capability parity with the reference's component model
(lib/runtime/src/component.rs:99-345, component/client.rs:52-319):

- workers register endpoint *instances* in the statestore under a lease;
  lease expiry removes them and every watching client drops them live —
  but the store's word is a CACHE, not an authority: on a store outage
  (or a store restarted empty) clients freeze the last-known-good set and
  let the RPC health probes arbitrate (runtime/control_plane.py,
  docs/resilience.md §Control-plane blackout)
- clients watch the instance prefix and route Random / RoundRobin / Direct /
  KV-aware across live instances
- namespaced pub/sub events (`{ns}.{subject}`) carry KV cache events and
  worker metrics

Key layout in the statestore:
  {ns}/components/{comp}/endpoints/{ep}/instances/{instance_id} → InstanceInfo
  {ns}/models/{kind}/{name}                                     → ModelEntry
"""

from __future__ import annotations

import asyncio
import json
import logging
import random
import sys
import time
import uuid
from dataclasses import dataclass
from typing import Any, AsyncIterator, Callable, Dict, List, Optional

from dynamo_tpu.runtime.envknobs import env_str

from dynamo_tpu.runtime import control_plane, straggler, telemetry, tracing
from dynamo_tpu.runtime.admission import LoadSnapshot, OverloadedError
from dynamo_tpu.runtime.control_plane import ControlPlaneUnavailable
from dynamo_tpu.runtime.annotated import Annotated
from dynamo_tpu.runtime.bus import MessageBusClient
from dynamo_tpu.runtime.engine import AsyncEngine, Context
from dynamo_tpu.runtime.health import (
    QUARANTINED,
    STRAGGLER_SOURCE,
    SUSPECT,
    UNHEALTHY,
    HealthMonitor,
    HealthPolicy,
)

# health states routers must never dispatch to: unhealthy (wedged/stalled)
# and quarantined (integrity plane latched — outputs untrusted). SUSPECT
# (fail-slow plane, docs/resilience.md §Fail-slow) is deliberately NOT
# here: a suspect worker still serves correct bytes, merely slowly — it is
# soft-demoted in _pick (route of last resort), never hard-cut, so an
# all-slow fleet keeps serving. Consumers must compare against this tuple
# (or _is_unhealthy), never string-match health states themselves.
EXCLUDED_HEALTH = (UNHEALTHY, QUARANTINED)
from dynamo_tpu.runtime.resilience import (
    DEADLINE_ERROR,
    AllInstancesFailed,
    CircuitBreaker,
    Deadline,
    DeadlineExceeded,
    NoHealthyInstances,
    ResiliencePolicy,
    RetryableRpcError,
    StreamJournal,
    WorkerStalled,
    note_resume,
)
from dynamo_tpu.runtime.rpc import RpcClient, RpcServer
from dynamo_tpu.runtime.statestore import Lease, StateStoreClient, WatchEvent

logger = logging.getLogger(__name__)

KV_EVENTS_SUBJECT = "kv_events"
KV_METRICS_SUBJECT = "kv_metrics"
KV_HIT_RATE_SUBJECT = "kv_hit_rate"


def hit_rate_sink(ns) -> "Callable":
    """A KvRouter.on_hit_rate sink publishing KVHitRateEvents on the
    namespace `kv_hit_rate` subject. Holds strong task references (the loop
    only keeps weak ones) and swallows publish failures quietly — a bus
    outage must not spam the request hot path."""
    loop = asyncio.get_running_loop()
    inflight: set = set()

    async def _publish(payload: dict) -> None:
        try:
            await ns.publish(KV_HIT_RATE_SUBJECT, payload)
        except Exception:
            logger.debug("hit-rate publish failed", exc_info=True)

    def sink(ev) -> None:
        task = loop.create_task(_publish(ev.to_dict()))
        inflight.add(task)
        task.add_done_callback(inflight.discard)

    return sink


async def resubscribe_forever(ns, subject: str, apply) -> None:
    """Deliver each JSON payload on a namespace subject to ``apply(dict)``,
    resubscribing with exponential backoff across bus outages — a bus hiccup
    must never silently starve a consumer. One malformed payload is logged
    and skipped, not fatal. Shared by the KV router feed, the standalone
    router component, and the metrics aggregator."""
    backoff = 0.5
    while True:
        try:
            sub = await ns.subscribe(subject)
            backoff = 0.5
            async for raw in sub:
                try:
                    apply(json.loads(raw) if isinstance(raw, (bytes, str)) else raw)
                except (ValueError, KeyError, TypeError):
                    logger.warning("malformed %s payload", subject, exc_info=True)
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.warning("%s subscription lost; retrying", subject, exc_info=True)
        await asyncio.sleep(backoff)
        backoff = min(backoff * 2, 10.0)


def parse_endpoint_path(path: str) -> tuple:
    """dyn://ns.comp.ep → (ns, comp, ep). Reference: protocols.rs:33-302."""
    p = path
    if p.startswith("dyn://"):
        p = p[len("dyn://"):]
    parts = p.split(".")
    if len(parts) != 3 or not all(parts):
        raise ValueError(f"invalid endpoint path {path!r} (want dyn://ns.component.endpoint)")
    return parts[0], parts[1], parts[2]


@dataclass
class InstanceInfo:
    instance_id: str
    address: str  # host:port of the worker's rpc server
    worker_id: str
    # overload-protection extras, refreshed by the worker's load-report
    # heartbeat (re-put of this key): routers stop dispatching to draining
    # instances and prefer the least-loaded ones. Optional on the wire so
    # entries written by older workers still parse.
    draining: bool = False
    load: Optional[dict] = None  # LoadSnapshot wire form
    # health plane (runtime/health.py): self-checked state, wall-clock time
    # of the last heartbeat re-put, and the monitor's stall/reap counters —
    # `llmctl worker health` reads exactly these keys
    health: str = "healthy"
    ts: float = 0.0
    health_counters: Optional[dict] = None
    # wall-clock registration time, stamped once at serve(): `llmctl worker
    # list` renders uptime from it. 0.0 from pre-PR6 workers (tolerated).
    started: float = 0.0

    def to_json(self) -> bytes:
        return json.dumps(self.__dict__).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "InstanceInfo":
        d = json.loads(raw)
        return cls(
            **{k: d[k] for k in ("instance_id", "address", "worker_id")},
            draining=bool(d.get("draining", False)),
            load=d.get("load") if isinstance(d.get("load"), dict) else None,
            health=str(d.get("health", "healthy")),
            ts=float(d.get("ts") or 0.0),
            health_counters=(
                d.get("health_counters")
                if isinstance(d.get("health_counters"), dict) else None
            ),
            started=float(d.get("started") or 0.0),
        )


async def live_instance_infos(store, endpoint: str) -> List["InstanceInfo"]:
    """Parsed instance entries registered under a ``dyn://ns.comp.ep``
    endpoint, unparseable entries skipped, in stable (key-sorted) dial
    order — the shared front half of every "dial the first reachable
    instance" loop (`llmctl` status commands, the planner's
    AggregatorSource)."""
    ns, comp, ep = parse_endpoint_path(endpoint)
    entries = await store.get_prefix(
        f"{ns}/components/{comp}/endpoints/{ep}/instances/"
    )
    infos = []
    for key in sorted(entries):
        try:
            infos.append(InstanceInfo.from_json(entries[key]))
        except (ValueError, KeyError):
            continue
    return infos


class DistributedRuntime:
    """Per-process handle on the distributed planes.

    Reference: DistributedRuntime (lib/runtime/src/distributed.rs:32-155).
    """

    def __init__(self, store: StateStoreClient, bus: Optional[MessageBusClient],
                 advertise_host: str = "127.0.0.1"):
        self.store = store
        self.bus = bus
        self.worker_id = uuid.uuid4().hex[:12]
        self.advertise_host = advertise_host
        self._store_url: str = ""
        self._rpc_server: Optional[RpcServer] = None
        self._health_monitor = None  # runtime/health.py, created with the server
        self._primary_lease: Optional[Lease] = None
        self._closed = asyncio.Event()
        self._background: list = []
        # drain signal: load reporters re-put instance keys immediately on
        # a drain toggle instead of waiting out their heartbeat interval.
        # One event per reporter — a shared event would only wake whichever
        # reporter clears it first.
        self._drain_listeners: List[asyncio.Event] = []
        # who ordered the drain: "local" (SIGUSR1 / API) and/or "store"
        # (llmctl drain keys). Tracked separately so a statestore resync —
        # which only knows about keys — can never undo an operator's
        # signal-initiated drain, and vice versa.
        self._drain_sources: set = set()
        # live in-flight migration (disagg/migration.py): the coordinator
        # a serving worker attaches so a drain migrates its streams to
        # healthy siblings instead of holding the process hostage. None
        # (DYN_TPU_MIGRATE=0, or no attach_migration call) = exact old
        # drain semantics.
        self._migrator = None

    @classmethod
    async def create(
        cls,
        statestore_url: Optional[str] = None,
        bus_url: Optional[str] = None,
        advertise_host: Optional[str] = None,
    ) -> "DistributedRuntime":
        store_url = statestore_url or env_str("DYN_TPU_STATESTORE", "127.0.0.1:37901")
        b_url = bus_url or env_str("DYN_TPU_BUS", "127.0.0.1:37902")
        store = await cls._connect_store(store_url)
        bus: Optional[MessageBusClient] = None
        try:
            bus = await MessageBusClient.connect(b_url)
        except OSError:
            logger.warning("message bus unavailable at %s (events disabled)", b_url)
        rt = cls(store, bus, advertise_host or env_str("DYN_TPU_ADVERTISE_HOST", "127.0.0.1"))
        rt._store_url = store_url
        return rt

    @staticmethod
    async def _connect_store(store_url: str) -> StateStoreClient:
        """Dial the statestore, retrying inside the cold-start deadline.

        A store that stays dead past the deadline either (a) falls back to
        the disk discovery cache — the process cold-starts from the
        last-known-good view, marked stale, and reconnects to the store
        when it returns — or (b) raises the typed
        :class:`ControlPlaneUnavailable` so supervisors see a crisp
        failure instead of a hung or endlessly-crash-looping process
        (docs/resilience.md §Control-plane blackout)."""
        policy = control_plane.ControlPlanePolicy.from_env()
        t0 = time.monotonic()
        last: Optional[Exception] = None
        while True:
            try:
                return await StateStoreClient.connect(store_url)
            except OSError as e:
                last = e
            if time.monotonic() - t0 >= policy.cold_start_deadline:
                break
            await asyncio.sleep(min(0.25, policy.cold_start_deadline / 4))
        cache = control_plane.maybe_cache(policy)
        if cache is not None and await asyncio.to_thread(cache.has_any):
            logger.warning(
                "statestore %s unreachable for %.1fs — cold-starting from "
                "the discovery cache at %s (stale-serve; reconnecting in "
                "the background)", store_url, policy.cold_start_deadline,
                cache.root,
            )
            # cache_cold_starts is counted by the CONSUMERS that actually
            # load a view from disk (EndpointClient, ModelWatcher) — not
            # here too, or one process cold start would count N+1 times
            return await StateStoreClient.connect_lazy(store_url)
        raise ControlPlaneUnavailable(
            f"statestore {store_url} unreachable for "
            f"{policy.cold_start_deadline:.1f}s and no discovery cache to "
            f"cold-start from (set {control_plane.ENV_CACHE} on frontends "
            f"to survive control-plane outages): {last}"
        ) from last

    async def reconnect_store(self) -> None:
        try:
            await self.store.close()
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.debug("closing stale statestore client failed", exc_info=True)
        self.store = await StateStoreClient.connect(self._store_url)
        # reconnect_store is only ever called because a connection failed:
        # carry the outage stamp onto the replacement client so recovery
        # heuristics (rejoin jitter) still see the loss
        self.store.last_disconnect_at = time.monotonic()
        self._primary_lease = None

    # sync wrapper used by CLI code paths that build the runtime lazily
    @classmethod
    def from_settings(cls, statestore_url: Optional[str] = None, **kw) -> "DistributedRuntime":
        raise RuntimeError("use `await DistributedRuntime.create(...)` in async context")

    async def primary_lease(self) -> Lease:
        if self._primary_lease is None:
            self._primary_lease = await self.store.grant_lease()
        return self._primary_lease

    async def rpc_server(self) -> RpcServer:
        if self._rpc_server is None:
            self._rpc_server = RpcServer(host="0.0.0.0", port=0)
            await self._rpc_server.start()
            # the health plane rides the server: self-checks (engine
            # heartbeat, loop lag), the stuck-request reaper, and the
            # unhealthy→self-drain→recover cycle (drain source "health")
            self._health_monitor = HealthMonitor(
                server=self._rpc_server, set_draining=self.set_draining
            )
            self._rpc_server.health = self._health_monitor
            self._health_monitor.start()
        return self._rpc_server

    @property
    def health_monitor(self):
        return self._health_monitor

    @property
    def draining(self) -> bool:
        if self._rpc_server is not None:
            return self._rpc_server.draining
        return bool(self._drain_sources)

    def set_draining(self, flag: bool, source: str = "local") -> None:
        """Enter/leave drain mode: the RPC server rejects new requests with a
        retryable ``draining`` reply (in-flight streams keep running), and
        every endpoint's load reporter re-puts its instance key with the
        draining flag so routers stop dispatching new work here. SIGUSR1
        toggles the ``local`` source (runtime/worker.py); ``llmctl worker
        drain`` drives the ``store`` source via control keys. The worker
        drains while ANY source wants it — an undrain through one channel
        must not cancel a drain ordered through the other."""
        if flag:
            self._drain_sources.add(source)
        else:
            self._drain_sources.discard(source)
        effective = bool(self._drain_sources)
        if self._rpc_server is not None:
            self._rpc_server.set_draining(effective)
        logger.info(
            "worker %s %s (sources: %s)", self.worker_id,
            "DRAINING" if effective else "undrained",
            sorted(self._drain_sources) or "none",
        )
        for ev in self._drain_listeners:
            ev.set()
        # phased drain (docs/resilience.md §Live migration): with a
        # migration coordinator attached, entering drain kicks off the
        # migrate-inflight phase (admission is already stopped above);
        # undraining before the deadline cancels it and un-freezes
        if self._migrator is not None:
            if effective:
                self._migrator.notify_drain()
            else:
                self._migrator.cancel_drain()
        # chaos-plane observation hook (docs/chaos.md): one dict-get unless
        # runtime/chaos.py is imported and armed — serving code never
        # imports it
        ch = sys.modules.get("dynamo_tpu.runtime.chaos")
        if ch is not None:
            ch.note_event(
                "drain", worker=self.worker_id, draining=effective,
                source=source, flag=flag,
            )

    def set_migrator(self, coordinator) -> None:
        """Attach a live-migration coordinator (disagg/migration.py) —
        drains then migrate in-flight streams instead of waiting them out."""
        self._migrator = coordinator

    def namespace(self, name: str) -> "Namespace":
        return Namespace(self, name)

    async def wait_closed(self) -> None:
        await self._closed.wait()

    async def shutdown(self) -> None:
        for t in self._background:
            t.cancel()
        if self._migrator is not None:
            await self._migrator.stop()
        if self._health_monitor is not None:
            await self._health_monitor.stop()
        if self._primary_lease is not None:
            await self._primary_lease.revoke()
        if self._rpc_server is not None:
            await self._rpc_server.stop()
        if self.bus is not None:
            await self.bus.close()
        await self.store.close()
        self._closed.set()


class Namespace:
    def __init__(self, runtime: DistributedRuntime, name: str):
        self.runtime = runtime
        self.name = name

    def component(self, name: str) -> "Component":
        return Component(self, name)

    # -- scoped events (reference traits/events.rs:31-96) ---------------------

    def subject(self, subject: str) -> str:
        return f"{self.name}.{subject}"

    async def publish(self, subject: str, payload: Any) -> None:
        if self.runtime.bus is None:
            return
        raw = payload if isinstance(payload, bytes) else json.dumps(payload).encode()
        await self.runtime.bus.publish(self.subject(subject), raw)

    async def subscribe(self, subject: str):
        if self.runtime.bus is None:
            raise RuntimeError("message bus not connected")
        return await self.runtime.bus.subscribe(self.subject(subject))


class Component:
    def __init__(self, namespace: Namespace, name: str):
        self.namespace = namespace
        self.name = name

    @property
    def base_key(self) -> str:
        return f"{self.namespace.name}/components/{self.name}"

    async def create_service(self) -> None:
        await self.namespace.runtime.store.create(
            f"{self.base_key}/service", json.dumps({"name": self.name}).encode()
        )

    def endpoint(self, name: str) -> "Endpoint":
        return Endpoint(self, name)


class Endpoint:
    def __init__(self, component: Component, name: str):
        self.component = component
        self.name = name

    @property
    def instances_prefix(self) -> str:
        return f"{self.component.base_key}/endpoints/{self.name}/instances/"

    @property
    def drain_prefix(self) -> str:
        """Operator drain control keys: ``{drain_prefix}{worker_id}`` (or
        ``.../all``) present ⇒ that worker drains; deleted ⇒ undrain.
        Written without a lease (llmctl) so they survive the CLI process."""
        return f"{self.component.base_key}/endpoints/{self.name}/drain/"

    @property
    def quarantine_prefix(self) -> str:
        """Operator quarantine control keys (``llmctl worker quarantine``),
        same shape as drain keys: present ⇒ the named worker latches
        quarantine (integrity plane, docs/resilience.md §Silent
        corruption); an observed DELETE is the operator unquarantine — it
        clears every quarantine source including self-tripped ones and
        resets the trip window (the operator is vouching for the host)."""
        return f"{self.component.base_key}/endpoints/{self.name}/quarantine/"

    @property
    def straggler_prefix(self) -> str:
        """Fail-slow verdict keys (docs/resilience.md §Fail-slow):
        ``{ns}/straggler/{worker_id}`` = ``b"suspect"|b"confirmed"``,
        written under the telemetry aggregator's lease by its arbiter
        sync loop (so a dead arbiter's verdicts expire rather than wedge
        the fleet demoted). Namespace-scoped, not endpoint-scoped: a
        verdict is about the WORKER (its host is slow), not any one
        endpoint it serves."""
        return f"{self.component.namespace.name}/{straggler.CONTROL_PREFIX}/"

    @property
    def rpc_name(self) -> str:
        ns = self.component.namespace.name
        return f"{ns}.{self.component.name}.{self.name}"

    @property
    def path(self) -> str:
        return f"dyn://{self.rpc_name}"

    async def serve(
        self,
        engine: AsyncEngine,
        model_entry: Optional[dict] = None,
        lease: Optional[Lease] = None,
    ) -> InstanceInfo:
        """Register this process as an instance of the endpoint.

        A monitor task watches for lease loss (statestore restart / missed
        heartbeats) and re-registers with a fresh lease so the worker rejoins
        discovery instead of silently serving zero traffic.
        Reference: EndpointConfigBuilder::start (component/endpoint.rs:58-142).
        """
        rt = self.component.namespace.runtime
        server = await rt.rpc_server()
        server.register(self.rpc_name, engine)
        lease = lease or await rt.primary_lease()
        info = InstanceInfo(
            instance_id=lease.lease_id,
            address=f"{rt.advertise_host}:{server.port}",
            worker_id=rt.worker_id,
            started=time.time(),
        )
        keys = {self.instances_prefix + info.instance_id: info.to_json()}
        if model_entry is not None:
            kinds = model_entry.get("kinds") or [model_entry.get("kind", "chat")]
            name = model_entry.get("name", "model")
            for kind in kinds:
                entry = dict(model_entry, kind=kind, endpoint=self.path)
                entry.pop("kinds", None)
                # per-instance entry key (reference endpoint.rs:98-108 keys by
                # lease id too): N workers serving one model hold N entries,
                # and one worker's deregistration can't delete the model out
                # from under the others — the discovery watcher refcounts
                keys[
                    f"{self.component.namespace.name}/models/{kind}/{name}"
                    f"@{info.instance_id}"
                ] = json.dumps(entry).encode()
        for k, v in keys.items():
            await rt.store.put(k, v, lease=lease)
        self._leased_keys = keys  # add_leased_key extends this set
        self._serve_lease = lease
        rt._background.append(
            asyncio.create_task(self._reregister_on_lease_loss(rt, lease, info, keys))
        )
        rt._background.append(
            asyncio.create_task(self._load_report_loop(rt, server, info))
        )
        rt._background.append(asyncio.create_task(self._drain_control_loop(rt)))
        rt._background.append(
            asyncio.create_task(self._quarantine_control_loop(rt))
        )
        # fail-slow verdict latch (docs/resilience.md §Fail-slow): gated on
        # the knob — with DYN_TPU_STRAGGLER unset no loop, no watch, no
        # overhead (the zero-overhead contract)
        if straggler.enabled():
            rt._background.append(
                asyncio.create_task(self._straggler_control_loop(rt))
            )
        return info

    async def _load_report_loop(self, rt: "DistributedRuntime", server, info: InstanceInfo) -> None:
        """Statestore heartbeat: periodically re-put the instance key with a
        fresh load snapshot (+ draining flag). Every watching client gets
        the put event, so the router's load view rides the existing watch
        plane — no extra subscription. A drain toggle wakes the loop for an
        immediate re-put."""
        from dynamo_tpu.runtime.admission import _env_pos_float

        interval = _env_pos_float("DYN_TPU_LOAD_REPORT_INTERVAL", 2.0)
        wake = asyncio.Event()
        rt._drain_listeners.append(wake)
        try:
            while True:
                try:
                    await asyncio.wait_for(wake.wait(), interval)
                except asyncio.TimeoutError:
                    pass
                wake.clear()
                snap = server.load_snapshot()
                info.draining = snap.draining
                info.load = snap.to_wire()
                # health state + counters ride the same heartbeat key:
                # `llmctl worker health` and routers read them with zero
                # extra plane
                info.health = snap.health
                info.ts = time.time()
                if rt._health_monitor is not None:
                    info.health_counters = rt._health_monitor.counters()
                key = self.instances_prefix + info.instance_id
                payload = info.to_json()
                # keep the leased-key set fresh so re-registration after
                # lease loss re-publishes current load, not the
                # serve()-time snapshot
                self._leased_keys[key] = payload
                try:
                    await rt.store.put(key, payload, lease=self._serve_lease)
                except asyncio.CancelledError:
                    raise
                except (ConnectionError, RuntimeError, OSError):
                    logger.debug("load report put failed", exc_info=True)
        finally:
            # the listener list lives as long as the runtime; this reporter
            # doesn't — leaving the event behind would grow the list on
            # every serve cycle
            if wake in rt._drain_listeners:
                rt._drain_listeners.remove(wake)

    async def _drain_control_loop(self, rt: "DistributedRuntime") -> None:
        """Apply operator drain keys (``llmctl worker drain``): a key put
        under :attr:`drain_prefix` naming this worker (or ``all``) enters
        drain mode; its deletion undrains. A drain issued while this worker
        was down applies on arrival — but a restarted worker gets a fresh
        worker_id, so a stale per-worker drain key never wedges the
        replacement.

        On every (re)subscription — and on every delete event — the CURRENT
        key set is authoritative for the ``store`` drain source: an undrain
        (key delete) that happened while the watch was down never produces
        a delete event, and deleting ``.../all`` must not undrain a worker
        whose per-worker key still exists (or the reverse). Only the
        ``store`` source is touched: a SIGUSR1-initiated drain survives any
        number of statestore resyncs."""

        def _mine(key: str) -> bool:
            return key.rsplit("/", 1)[-1] in (rt.worker_id, "all")

        async def _apply_key_set() -> None:
            wanted = any(_mine(k) for k in
                         await rt.store.get_prefix(self.drain_prefix))
            rt.set_draining(wanted, source="store")

        backoff = 0.5
        while True:
            watcher = None
            try:
                try:
                    await rt.store.get("__ping__")
                except (ConnectionError, RuntimeError):
                    # the client may have given up reconnecting entirely
                    # (outage longer than its reconnect window): re-dial
                    await rt.reconnect_store()
                watcher = await rt.store.watch_prefix(
                    self.drain_prefix, include_existing=True
                )
                await _apply_key_set()
                backoff = 0.5  # healthy watch established
                async for ev in watcher:
                    if not _mine(ev.key):
                        continue
                    if ev.type == "put":
                        rt.set_draining(True, source="store")
                    else:
                        await _apply_key_set()
            except asyncio.CancelledError:
                raise
            except (ConnectionError, RuntimeError, OSError):
                logger.warning("drain watch for %s lost; retrying", self.path,
                               exc_info=True)
            finally:
                if watcher is not None:
                    # unregister from the client — an abandoned watcher
                    # leaks its event queue on every retry
                    try:
                        await watcher.cancel()
                    except asyncio.CancelledError:
                        raise
                    except Exception:
                        logger.debug("drain watcher cancel failed", exc_info=True)
            await asyncio.sleep(backoff)
            backoff = min(backoff * 2, 10.0)

    async def _quarantine_control_loop(self, rt: "DistributedRuntime") -> None:
        """Apply operator quarantine keys (``llmctl worker quarantine``).

        Semantics (docs/resilience.md §Silent corruption runbook):

        - key present (put event, or present at a watch (re)sync) ⇒ latch
          the ``store`` quarantine source — the health monitor flips the
          worker to ``quarantined`` on its next check;
        - an observed DELETE event ⇒ the operator unquarantine: clears
          EVERY source (including a self-tripped latch) and resets the trip
          window — this is the only way a trip-quarantined worker
          re-admits itself;
        - key absent at a (re)sync ⇒ only the ``store`` source clears: a
          watch reconnect must not silently lift a self-tripped quarantine
          nobody vouched for.

        The loop shares the drain loop's reconnect discipline; with the
        integrity plane disabled it still applies operator orders (an
        operator quarantining a DYN_TPU_KV_INTEGRITY=0 worker is making a
        call the knob must not veto)."""
        from dynamo_tpu.runtime import integrity

        def _mine(key: str) -> bool:
            return key.rsplit("/", 1)[-1] in (rt.worker_id, "all")

        async def _apply_key_set() -> None:
            present = any(_mine(k) for k in
                          await rt.store.get_prefix(self.quarantine_prefix))
            if present:
                integrity.tracker().quarantine(
                    "store", reason="operator quarantine key"
                )
            else:
                integrity.clear_quarantine(source="store")

        backoff = 0.5
        while True:
            watcher = None
            try:
                try:
                    await rt.store.get("__ping__")
                except (ConnectionError, RuntimeError):
                    await rt.reconnect_store()
                watcher = await rt.store.watch_prefix(
                    self.quarantine_prefix, include_existing=True
                )
                await _apply_key_set()
                backoff = 0.5
                async for ev in watcher:
                    if not _mine(ev.key):
                        continue
                    if ev.type == "put":
                        integrity.tracker().quarantine(
                            "store", reason="operator quarantine key"
                        )
                    elif getattr(ev, "resync", False):
                        # a resync-synthesized delete is the store failing
                        # to vouch for the key, NOT an operator order:
                        # reconcile conservatively from the current set
                        await _apply_key_set()
                    else:
                        # observed operator unquarantine: full clear + trip
                        # window reset — then reconcile against the keys
                        # that REMAIN (deleting the per-worker key while
                        # `.../all` still stands must re-latch the store
                        # source, not free the worker)
                        integrity.clear_quarantine()
                        await _apply_key_set()
            except asyncio.CancelledError:
                raise
            except (ConnectionError, RuntimeError, OSError):
                logger.warning(
                    "quarantine watch for %s lost; retrying", self.path,
                    exc_info=True,
                )
            finally:
                if watcher is not None:
                    try:
                        await watcher.cancel()
                    except asyncio.CancelledError:
                        raise
                    except Exception:
                        logger.debug(
                            "quarantine watcher cancel failed", exc_info=True
                        )
            await asyncio.sleep(backoff)
            backoff = min(backoff * 2, 10.0)

    async def _straggler_drain_pulse(self, rt: "DistributedRuntime") -> None:
        """Migrate-off-the-straggler (docs/resilience.md §Fail-slow): a
        CONFIRMED verdict fires one bounded drain PULSE under the
        dedicated ``straggler`` source. Entering drain kicks the PR12
        migration coordinator (when attached): in-flight streams re-home
        to faster siblings over the atomic migrate frame — zero recompute,
        byte-equal — and routers stop sending new work. Once the inflight
        set is empty (or the pulse deadline passes) the worker UNDRAINS:
        unlike quarantine its KV and outputs are trusted, so it stays in
        the pool as the soft-demoted route of last resort while the
        verdict stands, and auto-recovers fully when the arbiter clears
        it."""
        rt.set_draining(True, source=STRAGGLER_SOURCE)
        try:
            window = straggler.StragglerPolicy.from_env().window
            deadline = time.monotonic() + max(window, 1.0)
            while time.monotonic() < deadline:
                server = rt._rpc_server
                if server is not None and server.inflight_count == 0:
                    break
                await asyncio.sleep(0.05)
        finally:
            rt.set_draining(False, source=STRAGGLER_SOURCE)

    async def _straggler_control_loop(self, rt: "DistributedRuntime") -> None:
        """Latch fail-slow verdicts pushed by the telemetry aggregator's
        arbiter (keys under :attr:`straggler_prefix` naming this worker or
        ``all`` — the latter only for drills; the arbiter itself is
        strictly per-worker).

        Semantics (docs/resilience.md §Fail-slow):

        - key put ⇒ latch the verdict (health plane reports ``suspect``
          next check; routers soft-demote on the existing wire paths); a
          verdict newly reaching ``confirmed`` additionally fires ONE
          drain pulse (:meth:`_straggler_drain_pulse`) to migrate
          in-flight streams off;
        - key delete — observed OR resync-synthesized — ⇒ reconcile from
          the current key set. Unlike the quarantine loop there is no
          sticky self-tripped source to protect: verdicts are leased to
          the arbiter, an expired lease (arbiter death) must FAIL OPEN to
          ``ok`` — slowness is recoverable and a fleet with no arbiter
          has no differential evidence against anyone.
        """
        severity = {straggler.OK: 0, straggler.SUSPECT: 1,
                    straggler.CONFIRMED: 2}
        pulse: Optional[asyncio.Task] = None

        def _mine(key: str) -> bool:
            return key.rsplit("/", 1)[-1] in (rt.worker_id, "all")

        def _apply(state: str) -> None:
            nonlocal pulse
            prev = straggler.verdict()
            straggler.set_verdict(state)  # unknown states dropped + warned
            cur = straggler.verdict()
            if cur == straggler.CONFIRMED:
                if prev != straggler.CONFIRMED and (
                    pulse is None or pulse.done()
                ):
                    pulse = asyncio.create_task(
                        self._straggler_drain_pulse(rt)
                    )
            else:
                # demoted below confirmed (recovery, or an operator drill
                # downgrading): stop any running pulse and make sure the
                # straggler drain source is released
                if pulse is not None and not pulse.done():
                    pulse.cancel()
                if STRAGGLER_SOURCE in rt._drain_sources:
                    rt.set_draining(False, source=STRAGGLER_SOURCE)

        async def _apply_key_set() -> None:
            state = straggler.OK
            keys = await rt.store.get_prefix(self.straggler_prefix)
            for k, v in keys.items():
                if not _mine(k):
                    continue
                s = v.decode("utf-8", "replace")
                if severity.get(s, 0) > severity.get(state, 0):
                    state = s
            _apply(state)

        backoff = 0.5
        try:
            while True:
                watcher = None
                try:
                    try:
                        await rt.store.get("__ping__")
                    except (ConnectionError, RuntimeError):
                        await rt.reconnect_store()
                    watcher = await rt.store.watch_prefix(
                        self.straggler_prefix, include_existing=True
                    )
                    await _apply_key_set()
                    backoff = 0.5
                    async for ev in watcher:
                        if not _mine(ev.key):
                            continue
                        if ev.type == "put":
                            _apply(ev.value.decode("utf-8", "replace"))
                        else:
                            await _apply_key_set()
                except asyncio.CancelledError:
                    raise
                except (ConnectionError, RuntimeError, OSError):
                    logger.warning(
                        "straggler watch for %s lost; retrying", self.path,
                        exc_info=True,
                    )
                finally:
                    if watcher is not None:
                        try:
                            await watcher.cancel()
                        except asyncio.CancelledError:
                            raise
                        except Exception:
                            logger.debug(
                                "straggler watcher cancel failed",
                                exc_info=True,
                            )
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 10.0)
        finally:
            # worker shutdown: don't leave an orphaned pulse holding the
            # drain source
            if pulse is not None and not pulse.done():
                pulse.cancel()

    async def add_leased_key(self, key: str, value: bytes) -> None:
        """Register an extra key under the serve lease; it participates in
        re-registration after lease loss (e.g. the disagg transfer address)."""
        rt = self.component.namespace.runtime
        self._leased_keys[key] = value
        await rt.store.put(key, value, lease=self._serve_lease)

    async def _reregister_on_lease_loss(
        self, rt: DistributedRuntime, lease: Lease, info: InstanceInfo, keys: dict
    ) -> None:
        backoff = 0.5
        while True:
            await lease.lost.wait()
            logger.warning(
                "lease %s lost for %s — re-registering", lease.lease_id, self.path
            )
            # was this lease lost to a store OUTAGE rather than a plain
            # expiry? An outage means the whole fleet lost its leases
            # together and will re-register together — spread the writes
            # with deterministic per-worker jitter so a recovering store
            # isn't thundering-herded by its own fleet. A lone expiry
            # (store healthy throughout) pays nothing. THIS runtime's own
            # client history decides (not process-global state — another
            # runtime's blip in the same process must not tax us): either
            # the connection is still down, or it dropped recently (the
            # client reconnected to a restarted-empty store and the
            # keepalive answered "unknown lease").
            dropped_at = getattr(rt.store, "last_disconnect_at", None)
            outage = (
                not getattr(rt.store, "connected", True)
                or (
                    dropped_at is not None
                    and time.monotonic() - dropped_at
                    < control_plane.REJOIN_OUTAGE_WINDOW_S
                )
            )
            while True:
                try:
                    try:
                        await rt.store.get("__ping__")
                    except (ConnectionError, RuntimeError):
                        outage = True
                        await rt.reconnect_store()
                    if outage:
                        jitter = control_plane.ControlPlanePolicy.from_env(
                        ).rejoin_jitter
                        if jitter > 0:
                            delay = control_plane.rejoin_delay(
                                rt.worker_id, jitter
                            )
                            logger.info(
                                "store recovered; rejoining %s in %.2fs "
                                "(seeded jitter)", self.path, delay,
                            )
                            await asyncio.sleep(delay)
                        outage = False
                    lease = await rt.store.grant_lease()
                    rt._primary_lease = lease
                    self._serve_lease = lease
                    # instance id follows the lease: re-key the instance entry
                    old_instance_key = next(k for k in keys if "/instances/" in k)
                    keys.pop(old_instance_key)
                    info.instance_id = lease.lease_id
                    keys[self.instances_prefix + info.instance_id] = info.to_json()
                    for k, v in keys.items():
                        await rt.store.put(k, v, lease=lease)
                    logger.info("re-registered %s under lease %s", self.path, lease.lease_id)
                    backoff = 0.5
                    break
                except (ConnectionError, RuntimeError, OSError):
                    logger.warning("re-registration failed; retrying in %.1fs", backoff)
                    await asyncio.sleep(backoff)
                    backoff = min(backoff * 2, 10.0)

    async def client(self, mode: str = "random", **kw) -> "EndpointClient":
        c = EndpointClient(self, mode, **kw)
        await c.start()
        return c


class EndpointClient(AsyncEngine):
    """Routes requests across live endpoint instances.

    Modes: random | round_robin | direct:<instance_id> | kv
    (reference RouterMode, component/client.rs:216-319). KV mode routes
    token-level requests by prefix overlap via the kv_router stack fed from
    the namespace event plane; non-token requests fall back to round-robin.
    """

    def __init__(
        self,
        endpoint: Endpoint,
        mode: str = "random",
        kv_block_size: int = 16,
        route_token_fn: Optional[Callable[[dict], Optional[List[int]]]] = None,
        policy: Optional[ResiliencePolicy] = None,
        health_policy=None,
    ):
        self.endpoint = endpoint
        self.mode = mode
        self.kv_block_size = kv_block_size
        # kv mode: derives token_ids from requests that don't carry them
        # (e.g. raw OpenAI dicts at a frontend) so prefix routing still works
        self.route_token_fn = route_token_fn
        self.policy = policy or ResiliencePolicy()
        self.health_policy = health_policy or HealthPolicy.from_env()
        self._breaker = CircuitBreaker(
            threshold=self.policy.breaker_threshold,
            cooldown=self.policy.breaker_cooldown,
            half_open_probes=self.policy.breaker_half_open_probes,
        )
        self._retry_rng = self.policy.rng()
        # observability: how often the resilience layer actually worked
        self.stats = {"failures": 0, "failovers": 0, "deadline_expired": 0,
                      "overloaded": 0, "probes": 0, "probe_failures": 0,
                      "resumes": 0, "resume_failures": 0,
                      # live migration (docs/resilience.md §Live migration):
                      # directed re-homes onto a drain target's staged KV,
                      # and drain directives that degraded to plain resume
                      "migrations": 0, "migration_resumes": 0}
        self._instances: Dict[str, InstanceInfo] = {}
        # control-plane blackout tolerance (runtime/control_plane.py,
        # docs/resilience.md §Control-plane blackout): when the statestore
        # dies — or restarts empty and can no longer vouch for keys — the
        # last-known-good instance set is FROZEN (held in `_stale`) instead
        # of cleared, and the RPC health probes below become the liveness
        # authority. `_cache`, when enabled, persists the confirmed view to
        # disk so a frontend restarted mid-outage cold-starts from it.
        self._cp = control_plane.ControlPlanePolicy.from_env()
        self._cache = control_plane.maybe_cache(self._cp)
        self._cache_dirty = False
        # iid → monotonic time it was first marked stale: each entry gets
        # its OWN grace window (a set-level clock would deny grace to
        # entries marked while an older hold is still outstanding)
        self._stale: Dict[str, float] = {}
        self._cp_id = f"client-{uuid.uuid4().hex[:8]}"
        # active liveness probing (runtime/health.py): when an instance's
        # RPC plane goes silent for probe_idle, __ping__ it through the real
        # dispatch path. Statestore heartbeats do NOT count as liveness —
        # a zombie worker's asyncio loop keeps heartbeating while its serve
        # path is wedged; only reply/pong traffic proves the path.
        self._last_rpc_seen: Dict[str, float] = {}
        self._probe_failed: Dict[str, float] = {}  # iid → monotonic of failure
        self._probe_task: Optional[asyncio.Task] = None
        # per-instance load view: fed by reply piggybacks (freshest) and
        # instance-key heartbeats (watch events); drives `load` mode picks,
        # draining avoidance, and overload soft-ejects
        self._loads: Dict[str, LoadSnapshot] = {}
        self._avoid_until: Dict[str, float] = {}  # overload soft-eject, monotonic
        # stable worker_id → live instance_id: KV events/metrics are keyed by
        # worker_id (which survives lease loss), instances come and go
        self._by_worker: Dict[str, str] = {}
        self._conns: Dict[str, RpcClient] = {}
        self._rr = 0
        self._watcher = None
        self._watch_task: Optional[asyncio.Task] = None
        self._kv_task: Optional[asyncio.Task] = None
        self._router = None
        self._ready = asyncio.Event()
        self._closed = False
        self._warned_no_tokens = False

    VALID_MODES = ("random", "round_robin", "kv", "load")

    async def start(self) -> None:
        if self.mode not in self.VALID_MODES and not self.mode.startswith("direct:"):
            raise ValueError(
                f"unknown router mode {self.mode!r}; want one of "
                f"{self.VALID_MODES} or direct:<instance_id>"
            )
        rt = self.endpoint.component.namespace.runtime
        try:
            if not getattr(rt.store, "connected", True):
                # a lazily-connected store (cache-mode cold start) fails
                # fast here; the watch loop below keeps re-dialing
                raise ConnectionError("statestore disconnected")
            self._watcher = await rt.store.watch_prefix(
                self.endpoint.instances_prefix
            )
        except (ConnectionError, RuntimeError, OSError):
            if not await self._load_from_cache():
                raise ControlPlaneUnavailable(
                    f"statestore unreachable and no discovery cache for "
                    f"{self.endpoint.path}"
                )
        self._watch_task = asyncio.create_task(self._watch_loop())
        self._probe_task = asyncio.create_task(self._probe_loop())
        if self.mode == "kv":
            from dynamo_tpu.kv_router.router import KvRouter

            self._router = KvRouter(block_size=self.kv_block_size)
            if rt.bus is not None:
                self._kv_task = asyncio.create_task(self._kv_feed())
                # hit-rate telemetry: every routing decision publishes a
                # KVHitRateEvent (reference kv-hit-rate subject)
                self._router.on_hit_rate = hit_rate_sink(
                    self.endpoint.component.namespace
                )

    async def _watch_loop(self) -> None:
        """Consume watch events; if the statestore connection drops, reconnect
        and re-watch with a fresh snapshot (the worker side re-registers on
        lease loss — this is the client half of that recovery).

        Stale-but-safe discovery (docs/resilience.md §Control-plane
        blackout): with ``stale_serve`` on (the default), a store outage —
        or a store that restarted empty and now disavows every key — FREEZES
        the last-known-good instance set instead of clearing it. Held
        entries are marked stale; the RPC health probes, which never
        depended on the store, arbitrate liveness until the store's word is
        trustworthy again. Purge rules run after ``stale_grace``:
        superseded (the worker re-registered under a fresh lease) or
        probe-failed entries drop; probe-passing ones are held."""
        backoff = 0.5
        while not self._closed:
            if self._watcher is not None:
                async for ev in self._watcher:
                    iid = ev.key.rsplit("/", 1)[-1]
                    if ev.type == "put":
                        try:
                            info = InstanceInfo.from_json(ev.value)
                        except (ValueError, KeyError):
                            continue
                        self._instances[iid] = info
                        self._note_fresh(iid)
                        prev = self._by_worker.get(info.worker_id)
                        self._by_worker[info.worker_id] = iid
                        if prev not in (None, iid) and prev in self._stale:
                            # the worker re-registered under a fresh lease:
                            # its held pre-outage twin is positively
                            # superseded — drop it now, not at grace
                            await self._drop_instance(prev)
                        if info.load is not None:
                            # heartbeat re-put: adopt the worker's own load
                            self._loads[iid] = LoadSnapshot.from_wire(info.load)
                        self._ready.set()
                        self._cache_dirty = True
                    elif (
                        ev.resync and self._cp.stale_serve
                        and iid in self._instances
                    ):
                        # a delete the CLIENT synthesized while adopting a
                        # post-reconnect snapshot: the (possibly restarted-
                        # empty) store no longer vouches for this key, but
                        # nothing observed a real deletion. Hold the
                        # instance as stale; probes/grace arbitrate.
                        self._mark_stale({iid})
                    else:
                        await self._drop_instance(iid)
                if self._closed:
                    return
                # watcher ended: the statestore connection died.
                logger.warning(
                    "instance watch for %s lost; %s",
                    self.endpoint.path,
                    "serving last-known-good set (stale) while reconnecting"
                    if self._cp.stale_serve and self._instances
                    else "reconnecting",
                )
                if self._cp.stale_serve and self._instances:
                    self._mark_stale(set(self._instances))
            rt = self.endpoint.component.namespace.runtime
            while not self._closed:
                try:
                    try:
                        await rt.store.get("__ping__")
                    except (ConnectionError, RuntimeError):
                        await rt.reconnect_store()
                    self._watcher = await rt.store.watch_prefix(
                        self.endpoint.instances_prefix, include_existing=True
                    )
                    if self._cp.stale_serve:
                        # the held set stays routable: live workers
                        # re-confirm via the snapshot's puts (clearing their
                        # stale mark), re-registered ones supersede their
                        # old entries, dead ones fail probes and purge at
                        # grace. Breaker state survives — an instance that
                        # was failing before the blip must not get a clean
                        # slate from reconnecting to the store.
                        self._breaker.prune(self._instances)
                    else:
                        # pre-blackout behavior (DYN_TPU_STALE_SERVE=0):
                        # wholesale replacement — fresh snapshot repopulates
                        # as puts stream in; workers that died during the
                        # outage (no delete event ever) are purged here with
                        # their pooled RPC connections.
                        self._breaker.prune(self._instances)
                        self._instances.clear()
                        self._loads.clear()
                        self._avoid_until.clear()
                        self._last_rpc_seen.clear()
                        self._probe_failed.clear()
                        if self._router is not None:
                            for wid in self._by_worker:
                                self._router.remove_worker(wid)
                        self._by_worker.clear()
                        stale_conns = list(self._conns.values())
                        self._conns.clear()
                        for conn in stale_conns:
                            try:
                                await conn.close()
                            except asyncio.CancelledError:
                                raise
                            except Exception:
                                logger.debug(
                                    "closing stale worker conn failed",
                                    exc_info=True,
                                )
                        self._ready.clear()
                    backoff = 0.5
                    break
                except (ConnectionError, RuntimeError, OSError):
                    await asyncio.sleep(backoff)
                    backoff = min(backoff * 2, 10.0)

    async def _drop_instance(self, iid: str) -> None:
        """Remove one instance and all its satellite state (the delete-event
        path, also used by the stale purge)."""
        gone = self._instances.pop(iid, None)
        self._loads.pop(iid, None)
        self._avoid_until.pop(iid, None)
        self._last_rpc_seen.pop(iid, None)
        self._probe_failed.pop(iid, None)
        self._discard_stale(iid)
        self._breaker.forget(iid)
        conn = self._conns.pop(iid, None)
        if conn is not None:
            # a surviving instance at the SAME address inherits the pooled
            # connection: an instance id changing hands (worker
            # re-registered under a fresh lease — same process, same RPC
            # server) must not cut the live streams multiplexed on it
            new_home = None
            if gone is not None and not conn.closed:
                for other, info in self._instances.items():
                    if info.address == gone.address and other not in self._conns:
                        new_home = other
                        break
            if new_home is not None:
                conn.on_load = (
                    lambda wire, _iid=new_home: self._note_load(_iid, wire)
                )
                self._conns[new_home] = conn
            else:
                await conn.close()
        if gone is not None and self._by_worker.get(gone.worker_id) == iid:
            del self._by_worker[gone.worker_id]
            # only purge the router when the worker has no live
            # instance left (a re-registration overwrites the
            # mapping before the old instance key is deleted)
            if self._router is not None:
                self._router.remove_worker(gone.worker_id)
        if not self._instances:
            self._ready.clear()
        self._cache_dirty = True

    # -- stale-but-safe bookkeeping (control_plane) ------------------------

    @property
    def stale_since(self) -> Optional[float]:
        """Monotonic time of the OLDEST outstanding stale mark (None when
        nothing is held) — observability only; purge decisions use each
        entry's own clock."""
        return min(self._stale.values()) if self._stale else None

    def _mark_stale(self, iids: set) -> None:
        now = time.monotonic()
        for iid in iids:
            # keep the original mark time on re-marks (the probe tick
            # re-marks every held entry while the store stays down)
            self._stale.setdefault(iid, now)
        control_plane.state().note_stale_entries(self._cp_id, len(self._stale))

    def _note_fresh(self, iid: str) -> None:
        if iid in self._stale:
            self._discard_stale(iid)

    def _discard_stale(self, iid: str) -> None:
        if self._stale.pop(iid, None) is not None:
            control_plane.state().note_stale_entries(
                self._cp_id, len(self._stale)
            )

    async def _load_from_cache(self) -> bool:
        """Cold-start the instance set from the disk discovery cache
        (statestore down at client start). Entries are marked stale — the
        probes confirm or purge them. False when the cache is off/empty."""
        if self._cache is None:
            return False
        entries = await asyncio.to_thread(
            self._cache.load, self.endpoint.instances_prefix
        )
        if not entries:
            return False
        for key in sorted(entries):
            iid = key.rsplit("/", 1)[-1]
            try:
                info = InstanceInfo.from_json(entries[key])
            except (ValueError, KeyError):
                continue
            self._instances[iid] = info
            self._by_worker[info.worker_id] = iid
            if info.load is not None:
                self._loads[iid] = LoadSnapshot.from_wire(info.load)
        if not self._instances:
            return False
        self._mark_stale(set(self._instances))
        self._ready.set()
        control_plane.state().note_cache_serve()
        logger.warning(
            "cold-started %s from the discovery cache: %d instance(s), "
            "marked stale until the store confirms them",
            self.endpoint.path, len(self._instances),
        )
        return True

    def _stale_purge_due(self) -> List[str]:
        """Stale entries ripe for removal: past their OWN grace window AND
        either superseded by a fresh registration of the same worker or
        failing their liveness probe. Probe-passing entries are never
        purged — a worker the data plane can still reach outranks a
        silent store."""
        if not self._stale:
            return []
        now = time.monotonic()
        due = []
        for iid, marked_at in list(self._stale.items()):
            if now - marked_at < self._cp.stale_grace:
                continue
            info = self._instances.get(iid)
            if info is None:
                self._discard_stale(iid)
                continue
            superseded = self._by_worker.get(info.worker_id) != iid
            if superseded or iid in self._probe_failed:
                due.append(iid)
        return due

    async def _flush_cache(self) -> None:
        """Persist the CONFIRMED instance view (never the stale guesses —
        a cold start must seed from the last view the store vouched for).
        Runs off-thread; called from the probe loop when dirty."""
        if self._cache is None or not self._cache_dirty or self._stale:
            return
        self._cache_dirty = False
        entries = {
            self.endpoint.instances_prefix + iid: info.to_json()
            for iid, info in self._instances.items()
        }
        try:
            await asyncio.to_thread(
                self._cache.save, self.endpoint.instances_prefix, entries
            )
        except asyncio.CancelledError:
            raise
        except Exception:
            self._cache_dirty = True
            logger.debug("discovery cache write failed", exc_info=True)

    async def _kv_feed(self) -> None:
        """Feed KV events + metrics from the namespace event plane into the router."""
        from dynamo_tpu.kv_router.protocols import ForwardPassMetrics, RouterEvent

        ns = self.endpoint.component.namespace
        await asyncio.gather(
            resubscribe_forever(
                ns, KV_EVENTS_SUBJECT,
                lambda d: self._router.apply_event(RouterEvent.from_dict(d)),
            ),
            resubscribe_forever(
                ns, KV_METRICS_SUBJECT,
                lambda d: self._router.update_worker_metrics(
                    d["worker_id"], ForwardPassMetrics.from_dict(d["metrics"])
                ),
            ),
        )

    async def wait_for_instances(self, n: int = 1, timeout: float = 30.0) -> None:
        """Reference: Client::wait_for_endpoints (client.rs:205-215)."""

        async def _wait() -> None:
            while len(self._instances) < n:
                self._ready.clear()
                await self._ready.wait()

        # asyncio.wait_for, not asyncio.timeout: the latter is py3.11+ and
        # the supported floor is 3.10. Normalize the timeout type too —
        # asyncio.TimeoutError is a distinct class from builtin TimeoutError
        # until 3.11, and callers should not have to catch both.
        try:
            await asyncio.wait_for(_wait(), timeout)
        except asyncio.TimeoutError:
            raise TimeoutError(
                f"no {n} live instance(s) for {self.endpoint.path} "
                f"within {timeout:.0f}s"
            ) from None

    def instance_ids(self) -> List[str]:
        return sorted(self._instances)

    def _note_load(self, iid: str, wire: dict) -> None:
        """Adopt a load snapshot piggybacked on an RPC reply header. A reply
        is also proof of RPC-plane liveness: it refreshes the probe clock
        and clears a stale probe failure."""
        self._loads[iid] = LoadSnapshot.from_wire(wire)
        self._last_rpc_seen[iid] = time.monotonic()
        self._probe_failed.pop(iid, None)

    def _is_draining(self, iid: str) -> bool:
        info = self._instances.get(iid)
        if info is not None and info.draining:
            return True
        snap = self._loads.get(iid)
        return snap is not None and snap.draining

    def _is_unhealthy(self, iid: str) -> bool:
        """Worker-self-reported unhealthy OR quarantined (instance-key
        heartbeat or reply piggyback). Unhealthy/quarantined workers also
        self-drain, but the piggyback can land a heartbeat interval earlier
        — honor whichever arrives first. Quarantine (docs/resilience.md
        §Silent corruption) excludes harder than unhealthy: the worker's
        *outputs* are untrusted, not merely its latency."""
        info = self._instances.get(iid)
        if info is not None and info.health in EXCLUDED_HEALTH:
            return True
        snap = self._loads.get(iid)
        return snap is not None and snap.health in EXCLUDED_HEALTH

    def _is_suspect(self, iid: str) -> bool:
        """Fail-slow soft state (docs/resilience.md §Fail-slow): the
        worker carries a fleet-relative straggler verdict. Its outputs are
        trusted and it still serves — this is a soft-demotion preference
        in ``_pick`` (route of last resort), never the hard cut
        ``_is_unhealthy`` applies. Read from the same two wire paths
        (instance-key heartbeat, reply piggyback), whichever arrives
        first."""
        info = self._instances.get(iid)
        if info is not None and info.health == SUSPECT:
            return True
        snap = self._loads.get(iid)
        return snap is not None and snap.health == SUSPECT

    def _load_score(self, iid: str) -> float:
        snap = self._loads.get(iid)
        # unknown load = assume free: new instances get traffic immediately
        return snap.utilization() if snap is not None else 0.0

    def _pick(self, request: Any, exclude: frozenset = frozenset()) -> str:
        ids = sorted(self._instances)
        if not ids:
            raise NoHealthyInstances(f"no live instances for {self.endpoint.path}")
        if self.mode.startswith("direct:"):
            want = self.mode.split(":", 1)[1]
            if want not in self._instances:
                raise RuntimeError(f"instance {want} not live")
            return want
        candidates = [i for i in ids if i not in exclude]
        if not candidates:
            raise NoHealthyInstances(
                f"all {len(ids)} live instance(s) of {self.endpoint.path} "
                f"failed this request"
            )
        # drain/health-aware, strictly: a draining or self-reported
        # unhealthy instance gets NO new work (its in-flight streams
        # finish; that is the whole zero-downtime-restart contract, and an
        # unhealthy worker is proactively routed around before requests pay
        # for the discovery). If every live instance is out there is
        # nothing legal to pick.
        serving = [
            i for i in candidates
            if not self._is_draining(i) and not self._is_unhealthy(i)
        ]
        if not serving:
            raise NoHealthyInstances(
                f"all {len(candidates)} live instance(s) of "
                f"{self.endpoint.path} are draining or unhealthy"
            )
        candidates = serving
        # fail-slow soft demotion (docs/resilience.md §Fail-slow): prefer
        # workers without a straggler verdict — but unlike the serving cut
        # above this NEVER empties the pool: an all-suspect fleet keeps
        # serving (slow everywhere beats down). A minority suspect starved
        # of traffic recovers via the arbiter's probation decay, not here.
        brisk = [i for i in candidates if not self._is_suspect(i)]
        if brisk:
            candidates = brisk
        # probe-aware: skip instances whose last liveness probe failed
        # (zombie suspects), but — unlike the drain filter — fall back to
        # them when nothing else is left: a suspect beats a guaranteed
        # failure, and probes keep running to re-admit it
        responsive = [i for i in candidates if i not in self._probe_failed]
        if responsive:
            candidates = responsive
        # breaker-aware: skip open/exhausted instances, but if EVERY
        # candidate is ejected, fall back to the full candidate set — a
        # last-ditch try beats a guaranteed failure
        healthy = [i for i in candidates if self._breaker.available(i)]
        if healthy:
            candidates = healthy
        # overload soft-eject: prefer instances outside their retry_after
        # window; unlike the breaker this never blocks the last resort
        now = time.monotonic()
        rested = [i for i in candidates if self._avoid_until.get(i, 0.0) <= now]
        if rested:
            candidates = rested
        if self.mode == "load":
            best = min(self._load_score(i) for i in candidates)
            pool = [i for i in candidates if self._load_score(i) <= best + 1e-9]
            # rotate among equally-loaded instances so a cold start (no
            # load views yet) degrades to round-robin, not herd-on-first
            self._rr = (self._rr + 1) % len(pool)
            return pool[self._rr]
        if self.mode == "random":
            return random.choice(candidates)
        if self.mode == "kv" and self._router is not None:
            token_ids = None
            if isinstance(request, dict):
                token_ids = request.get("token_ids")
                if not token_ids and self.route_token_fn is not None:
                    try:
                        token_ids = self.route_token_fn(request)
                    except Exception:
                        logger.warning("route_token_fn failed", exc_info=True)
            if token_ids:
                # router workers are keyed by the stable worker_id; map the
                # decision back onto that worker's live instance
                decision = self._router.schedule(token_ids)
                if decision is not None:
                    iid = self._by_worker.get(decision.worker_id)
                    if iid in candidates:
                        return iid
            elif not self._warned_no_tokens:
                self._warned_no_tokens = True
                logger.warning(
                    "kv router mode got a request without token_ids and no "
                    "route_token_fn — falling back to round-robin (pass "
                    "--model-path to the frontend to enable prefix routing)"
                )
        # round_robin fallback
        self._rr = (self._rr + 1) % len(candidates)
        return candidates[self._rr]

    async def _probe_loop(self) -> None:
        """Actively ``__ping__`` instances whose RPC plane has been silent
        for ``health_policy.probe_idle`` seconds. A failed or timed-out
        probe marks the instance a zombie suspect (skipped by ``_pick``)
        and feeds the circuit breaker; probing continues so a recovered
        worker is re-admitted by its next successful pong."""
        idle = self.health_policy.probe_idle
        interval = min(max(idle / 2.0, 0.05), idle)
        rt = self.endpoint.component.namespace.runtime
        while True:
            await asyncio.sleep(interval)
            # stale-but-safe housekeeping rides the probe tick: while the
            # store connection is down, every held instance is running on
            # stale authority (the watcher may not end until the client's
            # reconnect window expires — staleness must not wait for it);
            # then purge entries the probes (or a fresh registration) have
            # ruled on, and persist the confirmed view to the cache
            if (
                self._cp.stale_serve and self._instances
                and not getattr(rt.store, "connected", True)
            ):
                self._mark_stale(set(self._instances))
            for iid in self._stale_purge_due():
                await self._drop_instance(iid)
            await self._flush_cache()
            now = time.monotonic()
            due = []
            for iid, info in list(self._instances.items()):
                if not info.ts and info.health_counters is None:
                    # pre-health-plane worker (no heartbeat stamp yet, or an
                    # old binary that drops unknown ops): probing it would
                    # time out forever and breaker-eject a healthy worker
                    continue
                last = self._last_rpc_seen.get(iid)
                if last is None:
                    # first sight: start the idle clock, don't probe yet
                    self._last_rpc_seen[iid] = now
                    continue
                if now - last >= idle:
                    due.append(iid)
            if not due:
                continue

            async def _safe(iid: str) -> None:
                try:
                    await self._probe_one(iid)
                except asyncio.CancelledError:
                    raise
                except Exception:
                    logger.debug("probe of %s failed unexpectedly", iid,
                                 exc_info=True)

            # concurrent: one wedged instance must not stall the sweep for a
            # full probe_timeout and delay every other detection/readmission
            await asyncio.gather(*[_safe(i) for i in due])

    async def _probe_one(self, iid: str) -> None:
        self.stats["probes"] += 1
        timeout = self.health_policy.probe_timeout
        # sampled BEFORE the await: the pong's own load piggyback clears the
        # suspect mark via _note_load while ping() is still in flight, so
        # checking afterwards would make probe-driven readmission dead code
        was_suspect = iid in self._probe_failed
        conn: Optional[RpcClient] = None
        try:
            conn = await self._conn(iid, timeout=timeout)
            pong = await conn.ping(timeout=timeout)
        except asyncio.CancelledError:
            raise
        except WorkerStalled:
            # socket alive, serve path wedged: THE zombie signature. Mark
            # the suspect and penalize the breaker — but keep the pooled
            # connection: in-flight streams on it may still be draining,
            # and closing it would error every one of them.
            self.stats["probe_failures"] += 1
            self._probe_failed[iid] = time.monotonic()
            self._breaker.record_failure(iid)
            return
        except KeyError:
            # the instance left the live set mid-probe: nothing to mark —
            # a suspect entry for a gone instance would linger forever
            self._probe_failed.pop(iid, None)
            return
        except (ConnectionError, OSError):
            # dead transport: drop the pooled conn so the next attempt
            # re-dials
            self.stats["probe_failures"] += 1
            self._probe_failed[iid] = time.monotonic()
            self._breaker.record_failure(iid)
            await self._evict_conn(iid, conn or self._conns.get(iid))
            return
        self._last_rpc_seen[iid] = time.monotonic()
        if pong.get("health") in EXCLUDED_HEALTH:
            # the worker answered (liveness proven — no breaker penalty)
            # but diagnosed itself unhealthy/quarantined: keep it out of
            # rotation
            self.stats["probe_failures"] += 1
            self._probe_failed[iid] = time.monotonic()
            return
        self._probe_failed.pop(iid, None)
        if was_suspect:
            # probe-driven recovery readmits the instance (clears the
            # probe-induced breaker failures); routine pongs deliberately
            # do NOT record_success (a worker failing real requests while
            # answering pings must still trip the breaker)
            self._breaker.record_success(iid)

    def health_summary(self) -> dict:
        """Instance-health rollup for the HTTP ``/health`` edge: how many
        live instances exist and how many are actually serving (not
        draining, not unhealthy, not a zombie suspect)."""
        ids = list(self._instances)
        draining = sum(1 for i in ids if self._is_draining(i))
        unhealthy = sum(
            1 for i in ids
            if self._is_unhealthy(i) or i in self._probe_failed
        )
        serving = sum(
            1 for i in ids
            if not self._is_draining(i) and not self._is_unhealthy(i)
            and i not in self._probe_failed
        )
        return {
            "instances": len(ids),
            "serving": serving,
            "draining": draining,
            "unhealthy": unhealthy,
            # fail-slow soft-demoted workers: counted SEPARATELY from
            # unhealthy (they still serve) and not subtracted from
            # `serving` — a suspect worker is a route of last resort, but
            # it is a route
            "suspect": sum(1 for i in ids if self._is_suspect(i)),
            # entries currently held on stale authority (store outage /
            # restart): still routable, probes arbitrating
            "stale": len(self._stale),
        }

    async def _conn(self, iid: str, timeout: Optional[float] = None) -> RpcClient:
        conn = self._conns.get(iid)
        if conn is None or conn.closed:
            conn = await RpcClient.connect(self._instances[iid].address, timeout=timeout)
            # freshest load signal: piggybacked on this worker's replies
            conn.on_load = lambda wire, _iid=iid: self._note_load(_iid, wire)
            self._conns[iid] = conn
        return conn

    async def _evict_conn(self, iid: str, conn: Optional[RpcClient]) -> None:
        """Drop ``conn`` from the pool — only if the pool still holds that
        exact connection. A slower failure handler must never close a fresh
        healthy conn that a concurrent request already re-dialed (its
        close() would error every in-flight stream on it)."""
        if conn is None or self._conns.get(iid) is not conn:
            return
        del self._conns[iid]
        try:
            await conn.close()
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.debug("closing failed worker conn", exc_info=True)

    async def generate(self, request: Context) -> AsyncIterator[Annotated]:
        """Route one request, absorbing worker churn.

        Pre-first-token, transport failures (refused dial, reset, stall,
        draining worker) fail over to the next instance within the policy's
        retry budget and deadline; repeatedly-failing instances are ejected
        by the circuit breaker until a half-open probe readmits them. After
        the first item reaches the caller the request is pinned — but a
        pinned TOKEN-LEVEL stream cut by a transport failure (reset, stall,
        worker killed mid-decode) is resumed on another healthy instance:
        the journal's ``prompt + emitted`` re-admits with a decremented
        token budget, so the caller sees an inter-token gap instead of a
        dead stream (``policy.resume_attempts``; 0 = exact pinned
        behavior). Engine-semantic errors, spent deadlines, and exhausted
        resume budgets still surface in-band as error envelopes, and the
        total deadline keeps bounding the stream.
        """
        payload = request.data
        if hasattr(payload, "to_dict"):
            payload = payload.to_dict()
        elif hasattr(payload, "model_dump"):
            payload = payload.model_dump(exclude_none=True)
        policy = self.policy
        deadline = Deadline.after(policy.request_timeout)
        # instance-pick span: one per request, covering every attempt.
        # Parented under the edge span riding the Context (or the ambient
        # contextvar span); the Context's trace carrier is then pointed at
        # THIS span so the worker's rpc.serve span nests under the routing
        # decision that produced it. Failovers/overloads become events —
        # the trace shows which instances were tried and why they fell over.
        route = tracing.start_span(
            "client.route",
            parent=request.context.trace or tracing.current_span(),
            attributes={"endpoint": self.endpoint.path, "mode": self.mode},
        )
        if route is not None:
            request.context.trace = route
        route_status = "error"
        try:
            async for item in self._generate_attempts(
                request, payload, deadline, route
            ):
                yield item
            route_status = "ok"
        except BaseException as e:
            route_status = _route_status_of(e)
            raise
        finally:
            if route is not None:
                route.end(route_status)

    def _note_resume_failed(self, journal) -> None:
        """A stream the resume machinery was responsible for still died
        in-band: count it once (the journal is disarmed so later exits on
        the same request can't double-count)."""
        if journal is not None and journal.viable and not journal.finished:
            journal.viable = False
            self.stats["resume_failures"] += 1
            note_resume(failed=True)

    async def _generate_attempts(
        self, request, payload, deadline, route
    ) -> AsyncIterator[Annotated]:
        policy = self.policy
        tried: set = set()
        attempt = 0
        last_err: Optional[BaseException] = None
        # mid-stream resume (docs/resilience.md §Mid-stream resume): only
        # token-level payloads get a journal, and only when the policy asks
        # for it — resume_attempts == 0 constructs NOTHING on this path
        # (the zero-overhead guard tests/test_resume.py asserts).
        journal: Optional[StreamJournal] = None
        if (
            policy.resume_attempts > 0
            and isinstance(payload, dict)
            and isinstance(payload.get("token_ids"), list)
        ):
            journal = StreamJournal(payload)
            request.context.journal = journal
        delivered = False  # any item reached the caller, across attempts
        resume_deadline: Optional[Deadline] = None  # starts at first resume
        # live migration (docs/resilience.md §Live migration): a draining
        # worker ends a stream with an in-band migrating marker; the next
        # admission is routed AT the named target (where the staged KV makes
        # it recompute-free) before falling back to ordinary picks
        directed: Optional[str] = None
        while True:
            if deadline.expired:
                self.stats["deadline_expired"] += 1
                err = DeadlineExceeded(
                    f"{DEADLINE_ERROR}: request budget "
                    f"({policy.request_timeout:.1f}s) spent after "
                    f"{attempt} attempt(s)"
                )
                if delivered:
                    # the caller already holds tokens: terminate the stream
                    # in-band instead of raising out of a live generator
                    yield Annotated.from_error(str(err))
                    return
                raise err from last_err
            try:
                iid = None
                if directed is not None:
                    # one directed attempt at the migration target; any
                    # failure afterwards routes normally (the stale migrate
                    # id is ignored by other engines — plain resume).
                    # Deliberately only the HARD health cut here: a SUSPECT
                    # target with this stream's KV already staged is a
                    # better home than a fast sibling that must recompute —
                    # suspect is a valid migration target of last resort
                    if (
                        directed in self._instances
                        and directed not in tried
                        and not self._is_unhealthy(directed)
                    ):
                        iid = directed
                    directed = None
                if iid is None:
                    try:
                        iid = self._pick(payload, exclude=frozenset(tried))
                    except NoHealthyInstances:
                        if not tried:
                            raise
                        # every live instance failed once this request: widen
                        # back to the full set for whatever budget remains
                        tried.clear()
                        iid = self._pick(payload)
            except NoHealthyInstances as e:
                if delivered:
                    self._note_resume_failed(journal)
                    yield Annotated.from_error(
                        f"stream lost mid-decode with no healthy instance "
                        f"to resume on: {e}"
                    )
                    return
                raise
            self._breaker.acquire(iid)
            if route is not None:
                route.set_attribute("instance", iid)
                route.set_attribute("attempts", attempt + 1)
                route.add_event("pick", instance=iid, attempt=attempt + 1)
                if self._is_suspect(iid):
                    # landed on a soft-demoted straggler anyway (route of
                    # last resort): make the trace say so — this event is
                    # how a slow stream is attributed to the fail-slow
                    # plane during incident review
                    route.add_event("soft_demote", instance=iid)
            # exactly-once breaker resolution: every exit that calls neither
            # record_success nor record_failure (deadline expiry, abandoned
            # generator, application-error first item, unexpected raise)
            # must release the half-open probe slot, or the instance stays
            # ejected forever
            resolved = False
            first_seen = False
            conn: Optional[RpcClient] = None
            try:
                try:
                    conn = await self._conn(
                        iid, timeout=deadline.bound(policy.connect_timeout)
                    )
                except KeyError:
                    raise RetryableRpcError(
                        f"instance {iid} left the live set"
                    ) from None
                directive: Optional[dict] = None
                async for item in conn.generate(
                    self.endpoint.rpc_name,
                    payload,
                    context=request,
                    deadline=deadline,
                    inter_item_timeout=policy.inter_item_timeout,
                    raise_transport=True,
                ):
                    if (
                        not item.is_error
                        and isinstance(item.data, dict)
                        and "migrating" in item.data
                    ):
                        # in-band migration marker from a draining worker:
                        # consumed HERE — never yielded, never journaled,
                        # never counted as a first item. The stream ends
                        # right after it; the directive is handled below.
                        d = item.data["migrating"]
                        directive = d if isinstance(d, dict) else {}
                        continue
                    if not first_seen:
                        first_seen = True
                        if route is not None:
                            route.add_event("first_item", instance=iid)
                        if not item.is_error:
                            self._breaker.record_success(iid)
                            resolved = True
                    if journal is not None and not item.is_error:
                        # journal BEFORE the yield: a consumer cancelling
                        # mid-delivery must not lose the token it received
                        journal.note(item.data)
                    delivered = True
                    yield item
                if not first_seen:
                    self._breaker.record_success(iid)  # clean empty stream
                    resolved = True
                if directive is None:
                    return
                # -- live migration re-home (never a torn stream) ---------
                # The draining source ended the stream with an explicit
                # directive. Re-admit: at the named target (staged KV ⇒
                # zero recompute) or via the ordinary resume path. Neither
                # consumes the failure-resume budget — nothing failed.
                if journal is None or not journal.viable:
                    self._note_resume_failed(journal)
                    yield Annotated.from_error(
                        "stream migrated by a draining worker but cannot "
                        "be re-admitted (resume disabled or non-token "
                        "stream)"
                    )
                    return
                rebuilt = journal.resume_request()
                expected = directive.get("emitted")
                if rebuilt is None or (
                    isinstance(expected, int)
                    and expected != len(journal.emitted)
                ):
                    self._note_resume_failed(journal)
                    yield Annotated.from_error(
                        "stream migrated by a draining worker but the "
                        "journal cannot rebuild it (budget spent or "
                        "delivered tokens diverge from the checkpoint)"
                    )
                    return
                journal.migrations += 1
                payload = rebuilt
                target = directive.get("instance")
                mid = directive.get("mid")
                if target and mid and not directive.get("resume"):
                    payload = dict(rebuilt, migrate=str(mid))
                    directed = str(target)
                    # the source verified the target against the store
                    # moments ago; our own watch may simply not have seen
                    # it yet (fresh instance after a rolling restart) —
                    # give discovery a bounded beat before falling back
                    # to an undirected pick
                    for _ in range(40):
                        if directed in self._instances:
                            break
                        await asyncio.sleep(0.05)
                    self.stats["migrations"] += 1
                    if route is not None:
                        route.set_attribute(
                            "migrations", journal.migrations
                        )
                        route.add_event(
                            "migrate", source=iid, target=str(target),
                            emitted=len(journal.emitted),
                        )
                    logger.info(
                        "request %s migrating from %s to %s "
                        "(%d tokens of staged history)", request.id, iid,
                        target, len(journal.emitted),
                    )
                else:
                    self.stats["migration_resumes"] += 1
                    if route is not None:
                        route.add_event(
                            "migrate_resume", source=iid,
                            error=str(directive.get("error", "")),
                        )
                    logger.warning(
                        "request %s cut over to resume by draining worker "
                        "%s (%s)", request.id, iid,
                        directive.get("error", "drain"),
                    )
                tried = {iid}
                attempt = 0
                continue
            except asyncio.CancelledError:
                raise
            except DeadlineExceeded as e:
                # budget spent — not the instance's fault, no breaker
                # penalty, and no resume either: a resumed admission would
                # be shed with the same spent deadline
                self.stats["deadline_expired"] += 1
                if first_seen or delivered:
                    yield Annotated.from_error(str(e))
                    return
                raise
            except OverloadedError as e:
                # the worker is healthy, just BUSY: a prompt typed rejection
                # proves liveness, so the breaker records a success (a
                # half-open probe answering OVERLOADED must re-admit, and an
                # overloaded fleet must never breaker-eject itself into a
                # smaller, even more overloaded one). Soft-eject instead:
                # avoid this instance for its retry_after hint and fail over.
                self._breaker.record_success(iid)
                resolved = True
                self.stats["overloaded"] += 1
                if route is not None:
                    route.add_event("overloaded", instance=iid,
                                    retry_after_ms=e.retry_after_ms)
                if getattr(e, "tenant", None):
                    # per-TENANT rate shed (runtime/qos.py): the quota is
                    # about the caller, not this worker — failing over
                    # would only drain the tenant's bucket on every
                    # sibling. Surface the 429 + per-tenant Retry-After
                    # immediately, and do NOT avoid the instance (it is
                    # happy to serve other tenants right now).
                    if delivered:
                        self._note_resume_failed(journal)
                        yield Annotated.from_error(str(e))
                        return
                    raise
                self._avoid_until[iid] = (
                    time.monotonic() + max(e.retry_after_ms, 1) / 1000.0
                )
                tried.add(iid)
                attempt += 1
                last_err = e
                if attempt >= policy.max_attempts:
                    if delivered:
                        # a resumed re-admission shed everywhere: the
                        # original stream is already flowing to the caller,
                        # so the overload must terminate it in-band
                        self._note_resume_failed(journal)
                        yield Annotated.from_error(str(e))
                        return
                    # surface the typed overload (not AllInstancesFailed) so
                    # the HTTP edge can answer 429 + Retry-After
                    raise
                self.stats["failovers"] += 1
                delay = deadline.bound(policy.backoff(attempt, self._retry_rng))
                if delay:
                    await asyncio.sleep(delay)
            except (ConnectionError, OSError) as e:
                if deadline.expired and not first_seen:
                    # the dial/read was cut by the request budget running
                    # out, not by the worker misbehaving: classify as
                    # deadline expiry — no breaker penalty for a healthy
                    # instance that merely got a ~0s connect window
                    self.stats["deadline_expired"] += 1
                    err = DeadlineExceeded(
                        f"{DEADLINE_ERROR}: request budget "
                        f"({policy.request_timeout:.1f}s) spent after "
                        f"{attempt + 1} attempt(s)"
                    )
                    if delivered:
                        yield Annotated.from_error(str(err))
                        return
                    raise err from e
                # refused/timed-out dial, reset, stall, draining worker
                self._breaker.record_failure(iid)
                resolved = True
                self.stats["failures"] += 1
                if route is not None:
                    route.add_event("failover", instance=iid,
                                    error=f"{type(e).__name__}: {e}")
                if not isinstance(e, (RetryableRpcError, WorkerStalled)):
                    # the transport itself failed: drop the pooled conn so
                    # the next attempt (or request) dials fresh. NOT on a
                    # stall or a retryable rejection — there the multiplexed
                    # connection itself is healthy, and closing it would
                    # kill every other in-flight stream to that worker.
                    # Identity-guarded: only this attempt's conn is evicted
                    await self._evict_conn(iid, conn)
                if first_seen:
                    # tokens already delivered and THIS attempt's stream
                    # died a transport death: re-admit elsewhere as
                    # prompt+generated (never on engine-semantic errors —
                    # those arrive as in-band envelopes, not exceptions)
                    resumed = None
                    if (
                        journal is not None
                        and journal.resumes < policy.resume_attempts
                        and (resume_deadline is None
                             or not resume_deadline.expired)
                    ):
                        resumed = journal.resume_request()
                    if resumed is not None:
                        journal.resumes += 1
                        if resume_deadline is None:
                            # per-request resume budget: bounds total churn
                            # when workers keep dying under the stream
                            resume_deadline = Deadline.after(
                                policy.resume_budget_s
                            )
                        self.stats["resumes"] += 1
                        note_resume()
                        if route is not None:
                            route.set_attribute("resumes", journal.resumes)
                            route.add_event(
                                "resume", instance=iid,
                                emitted=len(journal.emitted),
                                error=f"{type(e).__name__}: {e}",
                            )
                        logger.warning(
                            "resuming request %s after mid-stream failure "
                            "on %s (%d emitted tokens re-seeded as prompt): "
                            "%s", request.id, iid, len(journal.emitted), e,
                        )
                        payload = resumed
                        # the resumed admission gets a fresh pre-first-token
                        # failover budget; only the dead instance is excluded
                        tried = {iid}
                        attempt = 0
                        last_err = e
                        delay = deadline.bound(
                            policy.backoff(1, self._retry_rng)
                        )
                        if delay:
                            await asyncio.sleep(delay)
                        continue
                    # not resumable (off, exhausted, non-token stream):
                    # failover would duplicate delivered tokens — surface
                    # the break in-band instead (exact pre-resume behavior)
                    self._note_resume_failed(journal)
                    yield Annotated.from_error(
                        f"connection to worker lost mid-stream: {e}"
                    )
                    return
                tried.add(iid)
                attempt += 1
                last_err = e
                if attempt >= policy.max_attempts:
                    if delivered:
                        # a resumed re-admission burned its whole failover
                        # budget without a first token: terminate in-band
                        self._note_resume_failed(journal)
                        yield Annotated.from_error(
                            f"connection to worker lost mid-stream and "
                            f"resume failed on {len(tried)} instance(s): {e}"
                        )
                        return
                    raise AllInstancesFailed(
                        f"request failed on {len(tried)} instance(s) after "
                        f"{attempt} attempt(s): {e}"
                    ) from e
                self.stats["failovers"] += 1
                delay = deadline.bound(policy.backoff(attempt, self._retry_rng))
                if delay:
                    await asyncio.sleep(delay)
            finally:
                if not resolved:
                    self._breaker.release(iid)

    async def close(self) -> None:
        self._closed = True
        control_plane.state().forget_consumer(self._cp_id)
        if self._watch_task:
            self._watch_task.cancel()
        if self._probe_task:
            self._probe_task.cancel()
        if self._kv_task:
            self._kv_task.cancel()
        if self._watcher:
            await self._watcher.cancel()
        for c in self._conns.values():
            await c.close()


def _route_status_of(e: BaseException) -> str:
    """Terminal status of a client.route span from the exception that ended
    it — typed so the flight recorder pins the interesting ones."""
    if isinstance(e, DeadlineExceeded):
        return "deadline"
    if isinstance(e, OverloadedError):
        return "overloaded"
    if isinstance(e, (asyncio.CancelledError, GeneratorExit)):
        return "cancelled"
    if isinstance(e, (NoHealthyInstances, AllInstancesFailed)):
        return "failed_over"
    return "error"


class KvPublishBridge:
    """Thread-safe bridge: engine-thread KV events → namespace event plane.

    Implements the allocator's KvEventSink protocol. The engine's step loop
    runs on its own thread, so events are handed to the asyncio side via
    call_soon_threadsafe into a queue drained by a publisher task.
    """

    # bound on queued events: during a bus outage the publish blocks on the
    # client's reconnect machinery, so events pool here — drop-oldest keeps
    # worker memory flat (the router's radix view self-heals from later
    # stored/removed events; `dropped` is exported for the control-plane
    # status surfaces)
    MAX_QUEUE = 2048

    def __init__(self, namespace: Namespace, worker_id: str):
        from dynamo_tpu.kv_router.publisher import KvEventPublisher

        self._ns = namespace
        self._loop = asyncio.get_running_loop()
        self._queue: asyncio.Queue = asyncio.Queue(maxsize=self.MAX_QUEUE)
        self.dropped = 0
        self._cp_id = f"kv-events-{worker_id}"
        self._inner = KvEventPublisher(worker_id, self._enqueue)
        self._task = asyncio.create_task(self._drain())

    # KvEventSink protocol (called from the engine thread)
    def blocks_stored(self, parent_hash, blocks) -> None:
        self._inner.blocks_stored(parent_hash, blocks)

    def blocks_removed(self, block_hashes) -> None:
        self._inner.blocks_removed(block_hashes)

    def _enqueue(self, event) -> None:
        self._loop.call_soon_threadsafe(self._offer, event.to_dict())

    def _offer(self, payload: dict) -> None:
        while self._queue.full():
            try:
                self._queue.get_nowait()
            except asyncio.QueueEmpty:
                break
            self.dropped += 1
            # count the drop only — queue occupancy ebbs and flows on the
            # hot path and is not worth a lock per event
            control_plane.state().note_buffer(self._cp_id, 0, 1)
        self._queue.put_nowait(payload)

    async def _drain(self) -> None:
        while True:
            payload = await self._queue.get()
            try:
                await self._ns.publish(KV_EVENTS_SUBJECT, payload)
            except (ConnectionError, RuntimeError):
                logger.warning("kv event publish failed", exc_info=True)

    def close(self) -> None:
        self._task.cancel()


async def serve_stats_endpoint(endpoint: "Endpoint", engine) -> "InstanceInfo":
    """Register a ``stats`` endpoint on the same component serving the
    engine's ForwardPassMetrics snapshot on demand — the pull-based scrape
    plane (reference: NATS $SRV.STATS scrape + EndpointStatsHandler,
    service.rs:115-242). Push via attach_kv_publishing covers routing;
    this covers ad-hoc operator/aggregator polls."""

    class _StatsEngine(AsyncEngine):
        async def generate(self, request: Context):
            yield Annotated.from_data(engine.metrics_snapshot())

    stats_ep = endpoint.component.endpoint("stats")
    return await stats_ep.serve(_StatsEngine())


async def attach_kv_publishing(
    endpoint: Endpoint, engine, interval: float = 1.0, role: str = "decode",
    bind_admission: bool = True, bind_events: bool = True,
) -> KvPublishBridge:
    """Wire a serving engine's KV events + load metrics onto the event plane.

    Events/metrics are keyed by the runtime's *stable worker_id* — NOT the
    instance id, which changes when a lost lease forces re-registration;
    clients map worker_id → live instance via InstanceInfo. Reference
    analogue: KvEventPublisher + KvMetricsPublisher (SURVEY.md §3.5).
    ``role`` tags the snapshots with the worker's pool role ("decode" |
    "prefill" | "frontend") so the cluster rollup's per-pool breakdown —
    what the planner resizes — attributes this worker's capacity correctly.
    ``bind_admission=False`` skips pointing the process's RPC admission
    gate at this engine — a prefill worker co-hosted with a decode RPC
    server publishes its own metrics but must not steal the gate's
    capacity probe from the engine actually serving requests.
    ``bind_events=False`` additionally skips the KV event sink: this
    engine's cached blocks then never enter the router's prefix-affinity
    radix tree under this process's worker_id — a prefill-only pool's
    blocks are not servable prefix hits for routed decode requests, and
    in the co-hosted case they would inflate the decode worker's overlap
    score with pages it doesn't hold.
    """
    ns = endpoint.component.namespace
    worker_id = ns.runtime.worker_id
    bridge = KvPublishBridge(ns, worker_id)
    if bind_events and hasattr(engine, "set_event_sink"):
        engine.set_event_sink(bridge)
    if getattr(engine, "_fault_addr", None) == "engine":
        # label the engine's corrupt/poison fault gates with the stable
        # worker id so a drill can target ONE worker in a fleet
        engine._fault_addr = worker_id
    server = ns.runtime._rpc_server
    if (
        bind_admission and server is not None
        and hasattr(engine, "metrics_snapshot")
    ):
        # the RPC server registers the *wrapper* engine (no capacity API);
        # point its admission gate at the core engine's real capacity
        server.admission.engine_probe = engine.metrics_snapshot

    # bus-outage buffering (docs/resilience.md §Control-plane blackout):
    # snapshots produced while the bus is down are buffered (drop-oldest)
    # and flushed at recovery with an explicit `stale_s` age stamp — the
    # aggregator's diff discipline absorbs the backfill, and nothing
    # downstream mistakes it for fresh data. DYN_TPU_BUS_BUFFER=0 restores
    # the old drop-on-failure behavior.
    cp_policy = control_plane.ControlPlanePolicy.from_env()
    buffer = (
        control_plane.BoundedPublishBuffer(cp_policy.bus_buffer)
        if cp_policy.bus_buffer > 0 else None
    )
    buffer_id = f"metrics-{worker_id}"
    # cumulative drops attributed to THIS publisher (buffer.dropped is
    # reported as deltas to the process tracker and reset) — stamping the
    # process-global total instead would double-count on co-hosted
    # prefill+decode publishers, the same class of bug bind_admission
    # gating exists to prevent
    dropped_total = [0]

    def _note_buffer_state() -> None:
        dropped_total[0] += buffer.dropped
        control_plane.state().note_buffer(
            buffer_id, len(buffer), buffer.dropped
        )
        buffer.dropped = 0

    async def _bounded_publish(payload: dict) -> None:
        """Publish with a time bound when buffering is on: the bus client's
        transparent retry PARKS calls through an outage (they replay at
        reconnect), which would wedge the metrics loop for the whole
        outage and silently disable buffering for it. A timed-out publish
        raises like a connection loss; the parked request still replays at
        reconnect (a duplicate snapshot diffs to zero at the aggregator)."""
        if buffer is None:
            await ns.publish(KV_METRICS_SUBJECT, payload)
            return
        try:
            await asyncio.wait_for(
                ns.publish(KV_METRICS_SUBJECT, payload),
                timeout=max(interval * 2, 2.0),
            )
        except asyncio.TimeoutError:
            raise ConnectionError("bus publish timed out (outage?)") from None

    async def _publish_metrics(snap: dict) -> None:
        payload = {"worker_id": worker_id, "metrics": snap}
        bus = ns.runtime.bus
        if bus is None:
            return  # no event plane configured: nothing to buffer FOR
        if buffer is not None and not getattr(bus, "connected", True):
            buffer.push(payload)
            _note_buffer_state()
            return
        if buffer is not None and len(buffer):
            backlog = buffer.drain()
            for i, (age_s, old) in enumerate(backlog):
                old["metrics"]["stale_s"] = round(age_s, 3)
                try:
                    await _bounded_publish(old)
                except (ConnectionError, RuntimeError):
                    # bus died again mid-flush: rebuffer THIS item and the
                    # whole remaining backlog with their true ages — one
                    # failure must cost one timeout, not one per item
                    for a, p in backlog[i:]:
                        buffer.push(p, age_s=a)
                    break
            _note_buffer_state()
        try:
            await _bounded_publish(payload)
        except (ConnectionError, RuntimeError):
            if buffer is None:
                raise
            # the outage began mid-publish (the connected check passed):
            # this snapshot is buffered like any other dark-time snapshot
            buffer.push(payload)
            _note_buffer_state()

    async def metrics_loop():
        while True:
            await asyncio.sleep(interval)
            try:
                snap = engine.metrics_snapshot()
                # cluster attribution: model name (engines that know it) or
                # the component name; plus process uptime for dashboards
                snap.setdefault(
                    "model",
                    getattr(engine, "model_name", None)
                    or endpoint.component.name,
                )
                snap.setdefault("role", role)
                snap["uptime_s"] = round(telemetry.uptime_seconds(), 3)
                # mid-stream resume outcomes: process-global (every
                # EndpointClient in this process feeds the same counters),
                # so co-hosted clients — a frontend publishing metrics, a
                # decode worker dialing peers — report once, not per client
                from dynamo_tpu.runtime.resilience import resume_counters

                r_ok, r_bad = resume_counters()
                snap.setdefault("resume_total", r_ok)
                snap.setdefault("resume_failed_total", r_bad)
                # live-migration outcomes (disagg/migration.py): the SOURCE
                # side's migrate-outs — process-global like the resume
                # counters, imported lazily so non-migrating processes
                # never load the module
                import sys as _sys

                mig = _sys.modules.get("dynamo_tpu.disagg.migration")
                if mig is not None:
                    m_ok, m_bad, m_blocks = mig.migration_counters()
                    snap.setdefault("migrations_total", m_ok)
                    snap.setdefault("migrations_failed_total", m_bad)
                    snap.setdefault("migrate_kv_blocks_moved_total", m_blocks)
                # integrity plane (docs/resilience.md §Silent corruption):
                # process-global trip counters — zeros until anything ever
                # tripped, constructor-free (the zero-overhead guard)
                integ = _sys.modules.get("dynamo_tpu.runtime.integrity")
                if integ is not None:
                    ic = integ.counters()
                    snap.setdefault(
                        "kv_integrity_failures_total",
                        ic["kv_integrity_failures_total"],
                    )
                    snap.setdefault(
                        "watchdog_trips_total", ic["watchdog_trips_total"]
                    )
                # profiling plane (docs/observability.md §Profiling): the
                # process-global dispatch timeline's gauges, for engines
                # whose own snapshot doesn't carry them — constructor-free,
                # empty until anything armed DYN_TPU_PROFILE here
                prof = _sys.modules.get("dynamo_tpu.runtime.profiling")
                if prof is not None:
                    for k, v in prof.gauges().items():
                        snap.setdefault(k, v)
                if server is not None and bind_admission:
                    # the co-hosted RPC server's counters belong to the
                    # publisher that OWNS it; a bind_admission=False
                    # publisher (prefill worker beside a decode server)
                    # re-reporting them under its own worker_id/role would
                    # double-count cluster request/shed/tenant counters
                    # and attribute the decode queue to the prefill pool
                    # overload observability rides the same metrics stream
                    snap["rpc_queue_depth"] = server.inflight_count
                    snap["shed_requests"] = server.admission.shed
                    snap["draining"] = int(server.draining)
                    # per-tenant QoS view (docs/qos.md): the engine's
                    # occupancy split merged with the admission gate's
                    # admit/rate-limit counters — one `tenants` dict on
                    # the wire, empty-path free when QoS is off
                    tstats = server.admission.tenant_stats()
                    if tstats:
                        tenants = snap.setdefault("tenants", {})
                        for t, st in tstats.items():
                            tenants.setdefault(t, {}).update(st)
                    # request outcome counters for the cluster SLO engine
                    snap["requests_total"] = server.requests_total
                    snap["requests_errored"] = server.requests_errored
                    # health plane: state + stall/reap counters, so the KV
                    # scheduler and dashboards see zombies without a new
                    # subscription
                    snap["health_state"] = server.health_state()
                    if server.health is not None:
                        snap["stalls_total"] = server.health.stalls_total
                        snap["reaped_requests_total"] = (
                            server.health.reaped_requests_total
                        )
                if tracing.enabled():
                    # phase-latency summary (p50/p95/p99 per phase) rides
                    # the same stream; components/metrics.py renders it
                    summary = tracing.phase_summary()
                    if summary:
                        snap["phase_latency"] = summary
                # control-plane connectivity as seen from this process —
                # the rollup/llmctl `control-plane status` raw material
                snap.setdefault(
                    "control_plane_state", control_plane.state_name()
                )
                # per-PUBLISHER drop attribution (this buffer + the KV
                # event bridge this call owns); the rollup sums per worker,
                # so a process-global count here would double-count on
                # co-hosted prefill+decode publishers
                dropped = dropped_total[0] + bridge.dropped
                if buffer is not None:
                    dropped += buffer.dropped
                snap.setdefault("bus_dropped_events", dropped)
                await _publish_metrics(snap)
            except (ConnectionError, RuntimeError):
                logger.warning("kv metrics publish failed", exc_info=True)

    ns.runtime._background.append(asyncio.create_task(metrics_loop()))
    return bridge
