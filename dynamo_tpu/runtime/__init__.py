"""Distributed runtime: engine abstraction, pipelines, components, transports."""

from .annotated import Annotated, EngineStreamError
from .engine import AsyncEngine, Context, EngineContext, FnEngine, collect
from .pipeline import MapOperator, Operator, Pipeline, PipelineBuilder

__all__ = [
    "Annotated",
    "AsyncEngine",
    "Context",
    "EngineContext",
    "EngineStreamError",
    "FnEngine",
    "MapOperator",
    "Operator",
    "Pipeline",
    "PipelineBuilder",
    "collect",
]
