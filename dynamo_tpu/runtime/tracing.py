"""End-to-end distributed request tracing: spans, propagation, flight recorder.

The three observability tiers dynamo_tpu already has (frontend Prometheus,
worker push, namespace aggregator — SURVEY.md §5) answer "how is the fleet
doing"; none of them answers "where did THIS request's time go". This module
adds the request-scoped tier:

- **Span model** — zero-dependency: ``trace_id``/``span_id``/``parent_id``,
  monotonic start/end, typed phase names (:data:`PHASES`), attributes, and
  timestamped events (fault injections, failovers, first tokens).
- **Propagation** — a W3C-``traceparent``-compatible wire form
  (``00-<32hex>-<16hex>-<flags>``): the HTTP edge accepts it from incoming
  requests, the RPC client injects it into the existing JSON header
  (``runtime/rpc.py``), the RPC server extracts it, and the disagg planes
  carry it on :class:`~dynamo_tpu.disagg.protocols.RemotePrefillRequest` —
  so one request through disaggregated prefill/decode yields ONE trace.
- **Flight recorder** — a bounded per-process ring of completed traces
  (env-tunable via ``DYN_TPU_TRACE_*``; PR3-style clamping: malformed or
  non-positive values fall back to defaults). Slow, errored, reaped,
  deadline-expired, and failed-over traces are *pinned* preferentially in
  a separate bounded store so a burst of ordinary traffic cannot evict
  the trace you need for the postmortem (shed traces are recorded but
  unpinned — sheds arrive in storms and must not cycle the pinned store).
  Exportable as JSONL via the frontend ``/debug/traces`` endpoint and
  ``llmctl trace dump``; ``llmctl trace show`` renders the span tree.
- **Phase histograms** — every ended span with a ``phase`` feeds a shared
  latency histogram (the no-dep primitives from ``llm/http/metrics.py``),
  rendered on the frontend ``/metrics`` and summarized (p50/p95/p99) into
  the worker metrics stream for ``components/metrics.py``.

Hot-path contract: with ``DYN_TPU_TRACE=0`` (or ``false``) every
``start_span``/``record_span`` call returns ``None`` before allocating
anything — the request path makes **zero tracing allocations per token**
(asserted by ``tests/test_tracing.py``). Spans are per *phase*, never per
token, so even enabled tracing costs a handful of objects per request.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import re
import threading
import time
from contextvars import ContextVar
from typing import Any, Dict, Iterable, List, Optional, Tuple, Union

logger = logging.getLogger(__name__)

# typed phase names: span durations land in the phase-latency histogram
# under exactly these labels (docs/observability.md has the catalog)
PHASES = (
    "ttft",
    "queue_wait",
    "prefill",
    "decode",
    "inter_token",
    "kv_transfer",
    # frontend hot-path decomposition (docs/observability.md §Profiling):
    # incremental detokenization and SSE-chunk JSON serialization — the
    # two host-CPU parts of the per-token residue the PR5 histograms
    # couldn't see
    "detokenize",
    "serialize",
)

# span terminal statuses (free-form strings are allowed; these are the ones
# the recorder treats as "interesting" and pins). "overloaded" is
# deliberately NOT here: sheds arrive in storms, and a storm pinning
# thousands of shed traces would cycle the bounded pinned store and evict
# exactly the rare error/reaped traces pinning exists to protect — shed
# traces stay in the ordinary ring (and sheds are counted in metrics).
STATUS_OK = "ok"
PIN_STATUSES = frozenset(
    {"error", "deadline", "reaped", "cancelled", "failed_over"}
)

_TRACEPARENT_RE = re.compile(
    r"^[0-9a-f]{2}-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$"
)

# finer-than-default buckets: phase latencies span sub-ms (inter-token on a
# warm engine) to tens of seconds (long prefill)
PHASE_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


from dynamo_tpu.runtime.envknobs import env_flag as _env_flag  # noqa: E402


class TracePolicy:
    """The ``DYN_TPU_TRACE_*`` knob bundle (PR3-style clamping: malformed,
    zero, or negative values fall back to the defaults — a bad knob must
    degrade to sane behavior, never to an unbounded recorder or a disabled
    one the operator didn't ask for)."""

    __slots__ = ("enabled", "ring_size", "pinned_size", "slow_ms")

    def __init__(
        self,
        enabled: bool = True,
        ring_size: int = 256,
        pinned_size: int = 64,
        slow_ms: float = 2000.0,
    ):
        self.enabled = bool(enabled)
        self.ring_size = max(int(ring_size), 1)
        self.pinned_size = max(int(pinned_size), 1)
        self.slow_ms = float(slow_ms)

    @classmethod
    def from_env(cls) -> "TracePolicy":
        from dynamo_tpu.runtime.admission import _env_pos_float, _env_pos_int

        d = cls()
        return cls(
            enabled=_env_flag("DYN_TPU_TRACE", d.enabled),
            ring_size=_env_pos_int("DYN_TPU_TRACE_RING", d.ring_size),
            pinned_size=_env_pos_int("DYN_TPU_TRACE_PINNED", d.pinned_size),
            slow_ms=_env_pos_float("DYN_TPU_TRACE_SLOW_MS", d.slow_ms),
        )


def _new_trace_id() -> str:
    return os.urandom(16).hex()


def _new_span_id() -> str:
    return os.urandom(8).hex()


class Span:
    """One timed operation in a trace.

    ``start``/``_t0`` pair wall clock (for cross-process ordering in dumps)
    with ``time.perf_counter`` (for durations — hosts don't share clocks,
    monotonic deltas are the only honest latency). ``end()`` is idempotent
    and hands the finished span to the flight recorder + phase histogram.
    """

    __slots__ = (
        "trace_id", "span_id", "parent_id", "name", "phase", "start",
        "_t0", "duration_s", "status", "attributes", "events", "_ended",
    )

    def __init__(
        self,
        name: str,
        trace_id: str,
        span_id: str,
        parent_id: Optional[str],
        phase: Optional[str] = None,
        attributes: Optional[Dict[str, Any]] = None,
    ):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.phase = phase
        self.start = time.time()
        self._t0 = time.perf_counter()
        self.duration_s: Optional[float] = None
        self.status = STATUS_OK
        self.attributes: Dict[str, Any] = dict(attributes) if attributes else {}
        self.events: List[Dict[str, Any]] = []
        self._ended = False

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def add_event(self, name: str, **attrs: Any) -> None:
        ev: Dict[str, Any] = {
            "name": name,
            "t_ms": round((time.perf_counter() - self._t0) * 1e3, 3),
        }
        if attrs:
            ev.update(attrs)
        self.events.append(ev)

    def end(self, status: Optional[str] = None) -> None:
        if self._ended:
            return
        self._ended = True
        self.duration_s = time.perf_counter() - self._t0
        if status is not None:
            self.status = status
        _finish(self)

    def to_dict(self) -> dict:
        d: Dict[str, Any] = {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "name": self.name,
            "start": round(self.start, 6),
            "status": self.status,
        }
        if self.parent_id:
            d["parent_id"] = self.parent_id
        if self.phase:
            d["phase"] = self.phase
        if self.duration_s is not None:
            d["duration_ms"] = round(self.duration_s * 1e3, 3)
        if self.attributes:
            d["attributes"] = dict(self.attributes)
        if self.events:
            d["events"] = list(self.events)
        return d


ParentLike = Union[Span, Tuple[str, str], None]


def _resolve_parent(parent: ParentLike) -> Tuple[str, Optional[str]]:
    """(trace_id, parent_span_id) for a new span: inherit from a local Span,
    a (trace_id, span_id) wire context, or start a fresh root trace."""
    if isinstance(parent, Span):
        return parent.trace_id, parent.span_id
    if isinstance(parent, tuple) and len(parent) == 2:
        return parent[0], parent[1]
    return _new_trace_id(), None


class FlightRecorder:
    """Bounded in-process store of completed traces.

    Two tiers, both FIFO-bounded: the *ring* holds the most recent traces;
    traces containing a slow span (``>= slow_ms``) or any non-``ok``
    terminal status are promoted to the *pinned* store, which ordinary
    traffic never evicts — exactly the traces a postmortem needs. Spans
    arrive from multiple threads (the engine step thread records
    retroactive phase spans); a plain lock serializes them.
    """

    def __init__(self, policy: TracePolicy):
        self.policy = policy
        self._lock = threading.Lock()
        self._ring: Dict[str, dict] = {}    # insertion-ordered (py3.7+)
        self._pinned: Dict[str, dict] = {}
        self.dropped = 0  # traces evicted unpinned (observability of loss)

    def record(self, span: Span) -> None:
        entry_span = span.to_dict()
        slow = (
            span.duration_s is not None
            and span.duration_s * 1e3 >= self.policy.slow_ms
        )
        interesting = slow or span.status in PIN_STATUSES
        with self._lock:
            entry = self._pinned.get(span.trace_id)
            if entry is None:
                entry = self._ring.get(span.trace_id)
            if entry is None:
                entry = {"trace_id": span.trace_id, "spans": [], "pinned": False}
                self._ring[span.trace_id] = entry
            entry["spans"].append(entry_span)
            if interesting and not entry["pinned"]:
                entry["pinned"] = True
                self._ring.pop(span.trace_id, None)
                self._pinned[span.trace_id] = entry
            # FIFO eviction, each tier bounded independently
            while len(self._ring) > self.policy.ring_size:
                self._ring.pop(next(iter(self._ring)))
                self.dropped += 1
            while len(self._pinned) > self.policy.pinned_size:
                self._pinned.pop(next(iter(self._pinned)))
                self.dropped += 1

    def traces(
        self,
        limit: int = 0,
        trace_id: Optional[str] = None,
        errored: bool = False,
    ) -> List[dict]:
        """Most-recent-last list of trace entries (copies). ``trace_id``
        filters to one trace; ``limit`` keeps only the newest N;
        ``errored`` keeps only traces containing a non-``ok`` span (the
        ``GET /debug/traces?errored=1`` filter — slow-but-successful pinned
        traces are deliberately NOT matched)."""
        with self._lock:
            if trace_id is not None:
                entry = self._pinned.get(trace_id) or self._ring.get(trace_id)
                return [json.loads(json.dumps(entry))] if entry else []
            out = list(self._ring.values()) + list(self._pinned.values())
        if errored:
            out = [
                e for e in out
                if any(s.get("status", STATUS_OK) != STATUS_OK
                       for s in e["spans"])
            ]
        out.sort(key=lambda e: min(
            (s.get("start", 0.0) for s in e["spans"]), default=0.0
        ))
        if limit > 0:
            out = out[-limit:]
        return json.loads(json.dumps(out))

    def dump_jsonl(
        self,
        limit: int = 0,
        trace_id: Optional[str] = None,
        errored: bool = False,
    ) -> str:
        """One JSON object per line per trace — the export format of the
        debug endpoint and ``llmctl trace dump``."""
        return "\n".join(
            json.dumps(t, sort_keys=True)
            for t in self.traces(limit, trace_id, errored=errored)
        )

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring) + len(self._pinned)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._pinned.clear()
            self.dropped = 0


# ---------------------------------------------------------------------------
# module-global state (per-process: policy, recorder, phase histogram)
# ---------------------------------------------------------------------------

_POLICY = TracePolicy.from_env()
_RECORDER = FlightRecorder(_POLICY)
_PHASE_HIST = None  # lazy: llm.http.metrics.Histogram labeled by phase
_PHASE_HIST_LOCK = threading.Lock()

_CURRENT_SPAN: ContextVar[Optional[Span]] = ContextVar(
    "dyn_tpu_current_span", default=None
)
_REQUEST_ID: ContextVar[Optional[str]] = ContextVar(
    "dyn_tpu_request_id", default=None
)


def configure(policy: Optional[TracePolicy] = None) -> TracePolicy:
    """(Re)build the global policy + recorder — tests call this after
    monkeypatching ``DYN_TPU_TRACE_*``; the histogram is reset too so
    phase summaries are scoped to the configuration."""
    global _POLICY, _RECORDER, _PHASE_HIST
    _POLICY = policy or TracePolicy.from_env()
    _RECORDER = FlightRecorder(_POLICY)
    with _PHASE_HIST_LOCK:
        _PHASE_HIST = None
    return _POLICY


def enabled() -> bool:
    return _POLICY.enabled


def recorder() -> FlightRecorder:
    return _RECORDER


def policy() -> TracePolicy:
    return _POLICY


def _phase_hist():
    global _PHASE_HIST
    if _PHASE_HIST is None:
        # the no-dep metrics primitive; imported lazily so importing tracing
        # (which rpc.py does) never pulls the llm tree in at startup. The
        # lock makes the check-then-set atomic: the engine step thread and
        # the asyncio thread can race the first observation, and the loser's
        # orphan Histogram would silently drop its samples.
        from dynamo_tpu.llm.http.metrics import Histogram

        with _PHASE_HIST_LOCK:
            if _PHASE_HIST is None:
                _PHASE_HIST = Histogram(
                    "dynamo_phase_latency_seconds",
                    "Per-request phase latency from trace spans",
                    ("phase",),
                    buckets=PHASE_BUCKETS,
                )
    return _PHASE_HIST


def observe_phase(phase: str, seconds: float) -> None:
    """Feed one phase-latency sample (span end does this automatically for
    spans carrying a ``phase``)."""
    _phase_hist().observe(seconds, phase=phase)


def render_phase_metrics() -> str:
    """Prometheus text exposition of the phase-latency histogram (appended
    to the frontend ``/metrics`` by ``ServiceMetrics.render``)."""
    return "\n".join(_phase_hist().render()) + "\n"


def phase_summary() -> Dict[str, dict]:
    """Compact per-phase stats {count, sum_s, p50_ms, p95_ms, p99_ms,
    buckets} — published on the worker metrics stream
    (``attach_kv_publishing``) and recorded by ``bench.py``. Quantiles are
    bucket-interpolated (the usual Prometheus histogram_quantile estimate).
    ``buckets`` is the raw cumulative bucket-count vector (aligned with
    :data:`PHASE_BUCKETS` + Inf): the cluster telemetry aggregator
    (``components/telemetry_aggregator.py``) diffs successive snapshots to
    rebuild true windowed distributions — quantiles alone can't be merged
    across workers or windows."""
    hist = _phase_hist()
    out: Dict[str, dict] = {}
    for labels, (counts, total, sum_) in hist.snapshot().items():
        if total == 0:
            continue
        phase = labels[0] if labels else ""
        out[phase] = {
            "count": total,
            "sum_s": round(sum_, 6),
            "p50_ms": _bucket_quantile(hist.buckets, counts, total, 0.50),
            "p95_ms": _bucket_quantile(hist.buckets, counts, total, 0.95),
            "p99_ms": _bucket_quantile(hist.buckets, counts, total, 0.99),
            "buckets": list(counts),
        }
    return out


def _bucket_quantile(
    buckets: Tuple[float, ...], cumulative: List[int], total: int, q: float
) -> float:
    """Histogram-quantile estimate in ms from cumulative bucket counts."""
    rank = q * total
    prev_bound = 0.0
    prev_count = 0
    for bound, count in zip(buckets, cumulative):
        if count >= rank:
            if bound == float("inf"):
                return round(prev_bound * 1e3, 3)  # clamp to last finite bound
            span_count = count - prev_count
            frac = (rank - prev_count) / span_count if span_count else 1.0
            return round((prev_bound + (bound - prev_bound) * frac) * 1e3, 3)
        prev_bound = bound if bound != float("inf") else prev_bound
        prev_count = count
    return round(prev_bound * 1e3, 3)


# ---------------------------------------------------------------------------
# span creation / context propagation
# ---------------------------------------------------------------------------


def start_span(
    name: str,
    parent: ParentLike = None,
    phase: Optional[str] = None,
    attributes: Optional[Dict[str, Any]] = None,
) -> Optional[Span]:
    """Begin a span (None when tracing is disabled — callers guard with
    ``if span is not None``, which is the whole disabled-mode cost)."""
    if not _POLICY.enabled:
        return None
    trace_id, parent_id = _resolve_parent(parent)
    return Span(name, trace_id, _new_span_id(), parent_id, phase, attributes)


def record_span(
    name: str,
    start_perf: float,
    end_perf: float,
    parent: ParentLike = None,
    phase: Optional[str] = None,
    attributes: Optional[Dict[str, Any]] = None,
    status: str = STATUS_OK,
) -> Optional[Span]:
    """Record a span retroactively from two ``perf_counter`` readings — the
    engine step thread stamps timestamps on its hot path and builds the
    spans once, at request finish (keeping dispatch loops allocation-free)."""
    if not _POLICY.enabled:
        return None
    span = start_span(name, parent=parent, phase=phase, attributes=attributes)
    now = time.perf_counter()
    span.start = time.time() - (now - start_perf)
    span._t0 = start_perf
    span._ended = True
    span.duration_s = max(end_perf - start_perf, 0.0)
    span.status = status
    _finish(span)
    return span


def record_event_span(
    name: str,
    parent: ParentLike = None,
    status: str = STATUS_OK,
    attributes: Optional[Dict[str, Any]] = None,
) -> Optional[Span]:
    """A zero-duration marker span — how shed (429) and malformed requests
    still leave a trace without ever being served."""
    if not _POLICY.enabled:
        return None
    now = time.perf_counter()
    return record_span(
        name, now, now, parent=parent, attributes=attributes, status=status
    )


def _finish(span: Span) -> None:
    _RECORDER.record(span)
    if span.phase and span.duration_s is not None:
        try:
            observe_phase(span.phase, span.duration_s)
        except Exception:  # a metrics hiccup must never fail the request
            logger.debug("phase observe failed", exc_info=True)


@contextlib.contextmanager
def span(
    name: str,
    parent: ParentLike = None,
    phase: Optional[str] = None,
    attributes: Optional[Dict[str, Any]] = None,
    set_current: bool = False,
):
    """Scoped span: ends (status ``error`` on exception) when the block
    exits. With ``set_current`` the span becomes the contextvar current
    span for the block (log correlation + child parenting)."""
    s = start_span(name, parent=parent, phase=phase, attributes=attributes)
    token = _CURRENT_SPAN.set(s) if (s is not None and set_current) else None
    try:
        yield s
    except BaseException as e:
        if s is not None:
            s.set_attribute("error", f"{type(e).__name__}: {e}")
            s.end(status="error")
        raise
    else:
        if s is not None:
            s.end()
    finally:
        if token is not None:
            _CURRENT_SPAN.reset(token)


def current_span() -> Optional[Span]:
    return _CURRENT_SPAN.get()


def set_current(span_: Optional[Span]):
    """Install ``span_`` as the contextvar current span; returns the reset
    token. Callers (one coroutine = one request) reset in ``finally``."""
    return _CURRENT_SPAN.set(span_)


def reset_current(token) -> None:
    _CURRENT_SPAN.reset(token)


def set_request_id(request_id: Optional[str]):
    return _REQUEST_ID.set(request_id)


def reset_request_id(token) -> None:
    _REQUEST_ID.reset(token)


def current_ids() -> Tuple[Optional[str], Optional[str]]:
    """(trace_id, request_id) of the calling context — the logging filter
    (``logging_util.TraceContextFilter``) stamps these onto every record."""
    s = _CURRENT_SPAN.get()
    return (s.trace_id if s is not None else None), _REQUEST_ID.get()


# ---------------------------------------------------------------------------
# W3C traceparent wire form
# ---------------------------------------------------------------------------


def format_traceparent(ctx: ParentLike) -> Optional[str]:
    """``00-<trace_id>-<span_id>-01`` for a Span or (trace_id, span_id)
    context; None when there is nothing to propagate."""
    if isinstance(ctx, Span):
        return f"00-{ctx.trace_id}-{ctx.span_id}-01"
    if isinstance(ctx, tuple) and len(ctx) == 2:
        return f"00-{ctx[0]}-{ctx[1]}-01"
    return None


def parse_traceparent(value: Any) -> Optional[Tuple[str, str]]:
    """(trace_id, span_id) from a traceparent header — None for absent or
    malformed input (the caller then starts a fresh root trace; a bad
    header from an old binary or a foreign proxy must never 500)."""
    if not isinstance(value, str):
        return None
    m = _TRACEPARENT_RE.match(value.strip().lower())
    if m is None:
        return None
    trace_id, span_id = m.group(1), m.group(2)
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None  # all-zero ids are invalid per the W3C spec
    return trace_id, span_id


# ---------------------------------------------------------------------------
# trace tree rendering (llmctl trace show)
# ---------------------------------------------------------------------------


def render_trace(entry: dict) -> str:
    """Indented span tree of one recorder entry — parentage by span ids,
    cross-process orphans (parent recorded elsewhere) rendered as roots."""
    spans = sorted(entry.get("spans", []), key=lambda s: s.get("start", 0.0))
    by_id = {s["span_id"]: s for s in spans}
    children: Dict[Optional[str], List[dict]] = {}
    for s in spans:
        parent = s.get("parent_id")
        key = parent if parent in by_id else None
        children.setdefault(key, []).append(s)

    lines = [f"trace {entry.get('trace_id', '?')}"
             f"{'  [pinned]' if entry.get('pinned') else ''}"]

    def walk(span_d: dict, depth: int) -> None:
        dur = span_d.get("duration_ms")
        dur_s = f"{dur:.1f}ms" if isinstance(dur, (int, float)) else "?"
        status = span_d.get("status", STATUS_OK)
        flag = "" if status == STATUS_OK else f"  !{status}"
        phase = span_d.get("phase")
        ph = f" [{phase}]" if phase else ""
        lines.append(f"{'  ' * (depth + 1)}{span_d['name']}{ph}  {dur_s}{flag}")
        for ev in span_d.get("events", []):
            extra = {k: v for k, v in ev.items() if k not in ("name", "t_ms")}
            suffix = f" {extra}" if extra else ""
            lines.append(
                f"{'  ' * (depth + 2)}@{ev.get('t_ms', 0):.1f}ms "
                f"{ev.get('name', '?')}{suffix}"
            )
        for child in children.get(span_d["span_id"], []):
            walk(child, depth + 1)

    for root in children.get(None, []):
        walk(root, 0)
    return "\n".join(lines)
