"""The `Annotated` stream envelope.

Every response item that crosses a process boundary is wrapped in an
SSE-compatible envelope carrying exactly one of: data, event, comment, or error.
Reference parity: lib/runtime/src/protocols/annotated.rs:32-150.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Generic, Optional, TypeVar

T = TypeVar("T")


@dataclass
class Annotated(Generic[T]):
    data: Optional[T] = None
    id: Optional[str] = None
    event: Optional[str] = None
    comment: list[str] = field(default_factory=list)

    ERROR_EVENT = "error"

    @classmethod
    def from_data(cls, data: T, id: Optional[str] = None) -> "Annotated[T]":
        return cls(data=data, id=id)

    @classmethod
    def from_error(cls, message: str, id: Optional[str] = None) -> "Annotated[T]":
        return cls(event=cls.ERROR_EVENT, comment=[message], id=id)

    @classmethod
    def from_annotation(cls, event: str, value: Any) -> "Annotated[T]":
        import json

        return cls(event=event, comment=[json.dumps(value)])

    @property
    def is_error(self) -> bool:
        return self.event == self.ERROR_EVENT

    def error_message(self) -> Optional[str]:
        if not self.is_error:
            return None
        return "; ".join(self.comment) if self.comment else "unknown error"

    def raise_on_error(self) -> "Annotated[T]":
        if self.is_error:
            raise EngineStreamError(self.error_message() or "engine error")
        return self

    # -- wire form ---------------------------------------------------------

    def to_dict(self) -> dict:
        out: dict[str, Any] = {}
        if self.data is not None:
            out["data"] = self.data
        if self.id is not None:
            out["id"] = self.id
        if self.event is not None:
            out["event"] = self.event
        if self.comment:
            out["comment"] = self.comment
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "Annotated[Any]":
        return cls(
            data=d.get("data"),
            id=d.get("id"),
            event=d.get("event"),
            comment=list(d.get("comment") or []),
        )


class EngineStreamError(RuntimeError):
    """An error annotation surfaced from a (possibly remote) engine stream."""
