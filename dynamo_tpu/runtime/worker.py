"""Worker main-wrapper: signal-driven graceful shutdown with a hard timeout.

Lifecycle on SIGTERM/SIGINT (re-designed from the reference's Worker,
`lib/runtime/src/worker.rs:59-211`):

1. deregister — the runtime's primary lease is revoked, deleting every
   lease-attached key (endpoint instances, model entries); client watchers
   drop the worker from the live set immediately, so no new requests route
   here;
2. drain — the RPC server stops accepting connections and waits for
   in-flight streams to finish (bounded);
3. close — the serving engine is shut down.

Exit codes:
- 0   clean shutdown (drain completed inside the window)
- 911 graceful-shutdown timeout overrun (the whole sequence exceeded
  ``DYN_TPU_GRACEFUL_SHUTDOWN_TIMEOUT``, default 30 s — same code the
  reference uses for the same condition)

A second signal during the drain skips straight to the hard exit.

SIGUSR1 toggles **drain mode** without exiting: the worker stays registered
(instance key re-put with ``draining: true``), routers stop dispatching new
work to it, the RPC server rejects stragglers with a retryable ``draining``
reply, and in-flight streams run to completion — the operator half of a
zero-downtime rolling restart (``llmctl worker drain`` does the same through
the statestore; docs/overload.md has the runbook).

The health plane (runtime/health.py) drives the same machinery through a
third, independent drain source: an ``unhealthy`` self-diagnosis (engine
stall, crash-looping subprocess engine) self-drains the worker and a
recovery streak undrains it — neither ever cancels a SIGUSR1 or llmctl
drain, because each source is tracked separately (docs/health.md).
"""

from __future__ import annotations

import asyncio
import inspect
import logging
import os
import signal
import sys

from dynamo_tpu.runtime.envknobs import env_pos_float

logger = logging.getLogger(__name__)

EXIT_OK = 0
EXIT_GRACEFUL_TIMEOUT = 911

DEFAULT_TIMEOUT = 30.0


def graceful_timeout() -> float:
    """Drain window before the hard exit. Malformed, zero, or negative env
    values clamp to the default — honoring ``0`` would turn every graceful
    shutdown into an instant 911, and a negative value is never meaningful."""
    return env_pos_float("DYN_TPU_GRACEFUL_SHUTDOWN_TIMEOUT", DEFAULT_TIMEOUT)


async def serve_until_shutdown(drt, engine=None) -> None:
    """Block until SIGTERM/SIGINT, then run the graceful sequence.

    ``drt`` is the DistributedRuntime whose shutdown() performs
    deregister→drain→close-transports; ``engine`` (optional) is closed after
    the runtime. Exits the process with the codes documented above.
    """
    loop = asyncio.get_running_loop()
    stop = asyncio.Event()
    signals_seen = 0

    def on_signal(signame: str) -> None:
        nonlocal signals_seen
        signals_seen += 1
        if signals_seen > 1:
            logger.warning("second %s during drain: hard exit", signame)
            os._exit(EXIT_GRACEFUL_TIMEOUT)
        logger.info("%s received: graceful shutdown begins", signame)
        stop.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, on_signal, sig.name)
        except (NotImplementedError, RuntimeError):  # non-main thread / platform
            pass

    def on_drain_toggle() -> None:
        drt.set_draining(not drt.draining)

    if hasattr(signal, "SIGUSR1") and hasattr(drt, "set_draining"):
        try:
            loop.add_signal_handler(signal.SIGUSR1, on_drain_toggle)
        except (NotImplementedError, RuntimeError):
            pass

    closed = asyncio.create_task(drt.wait_closed())
    stopped = asyncio.create_task(stop.wait())
    await asyncio.wait({closed, stopped}, return_when=asyncio.FIRST_COMPLETED)
    for t in (closed, stopped):
        t.cancel()

    timeout = graceful_timeout()

    async def _graceful() -> None:
        await drt.shutdown()  # lease revoke → RPC drain → transports
        if engine is not None and hasattr(engine, "close"):
            result = engine.close()
            if inspect.isawaitable(result):
                # async engines return a coroutine — awaiting it here is the
                # difference between real cleanup and silently skipping it
                await result

    try:
        # asyncio.wait_for, not asyncio.timeout: the latter is py3.11+ and
        # the supported floor is 3.10
        await asyncio.wait_for(_graceful(), timeout)
    except (TimeoutError, asyncio.TimeoutError):
        logger.error(
            "graceful shutdown exceeded %.0fs: exiting %d",
            timeout, EXIT_GRACEFUL_TIMEOUT,
        )
        sys.exit(EXIT_GRACEFUL_TIMEOUT)
    logger.info("worker shut down cleanly")
