"""Generic RAII object pool.

Items return to the pool when their handle is released — explicitly, via
context manager, or by garbage collection (a finalizer guards against
leaked handles). ``SharedPoolItem`` adds refcounted sharing: the item
returns when the LAST holder releases. This is the generic reuse
primitive the KV block allocator specializes (allocator.py is its own
implementation for the pool-critical path); use this one for everything
else that is expensive to create and cheap to reset.

Reference parity: Pool/PoolItem/SharedPoolItem (lib/runtime/src/utils/
pool.rs:23-427) — re-designed around Python context managers + weakref
finalizers instead of Drop impls.
"""

from __future__ import annotations

import logging
import threading
import weakref
from collections import deque
from typing import Any, Callable, Deque, Generic, Optional, TypeVar

logger = logging.getLogger(__name__)

T = TypeVar("T")


def _finalize_shared(pool: "Pool", value, state: dict) -> None:
    # no lock: the finalizer only runs once the handle is unreachable, so
    # no release() can race it
    if not state["returned"]:
        state["returned"] = True
        pool._return_value(value)


class PoolItem(Generic[T]):
    """A checked-out item; returns to its pool on release (once)."""

    def __init__(self, pool: "Pool[T]", value: T):
        self._pool = pool
        self.value = value
        self._released = False
        # guard against leaked handles: gc returns the item too
        self._finalizer = weakref.finalize(self, pool._return_value, value)

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        self._finalizer.detach()
        self._pool._return_value(self.value)

    def __enter__(self) -> T:
        return self.value

    def __exit__(self, *exc) -> None:
        self.release()


class SharedPoolItem(Generic[T]):
    """Refcounted handle: ``share()`` hands out another holder; the value
    returns to the pool when the last holder releases."""

    def __init__(self, pool: "Pool[T]", value: T):
        self._pool = pool
        self.value = value
        self._lock = threading.Lock()
        self._refs = 1
        self._state = {"returned": False}
        # leaked-handle guard: share() hands out THIS object, so if it is
        # garbage collected nobody can ever release — force-return then
        self._finalizer = weakref.finalize(
            self, _finalize_shared, pool, value, self._state
        )

    def share(self) -> "SharedPoolItem[T]":
        with self._lock:
            if self._state["returned"]:
                raise RuntimeError("cannot share a fully-released item")
            self._refs += 1
        return self

    def release(self) -> None:
        with self._lock:
            if self._state["returned"]:
                return
            self._refs -= 1
            if self._refs > 0:
                return
            self._state["returned"] = True
        self._finalizer.detach()
        self._pool._return_value(self.value)

    def __enter__(self) -> T:
        return self.value

    def __exit__(self, *exc) -> None:
        self.release()


class Pool(Generic[T]):
    """Bounded pool of reusable values.

    ``factory`` creates values on demand up to ``max_size`` live at once
    (None = unbounded); ``reset`` (optional) runs on every return before
    the value becomes reusable; ``acquire`` blocks until a value is free
    (or raises after ``timeout``)."""

    def __init__(
        self,
        factory: Callable[[], T],
        max_size: Optional[int] = None,
        reset: Optional[Callable[[T], None]] = None,
    ):
        self._factory = factory
        self._reset = reset
        self._max = max_size
        self._free: Deque[T] = deque()
        self._live = 0
        self._cond = threading.Condition()

    def acquire(self, timeout: Optional[float] = None) -> PoolItem[T]:
        return PoolItem(self, self._take(timeout))

    def acquire_shared(self, timeout: Optional[float] = None) -> SharedPoolItem[T]:
        return SharedPoolItem(self, self._take(timeout))

    def _take(self, timeout: Optional[float]) -> T:
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        with self._cond:
            while True:
                if self._free:
                    return self._free.popleft()
                if self._max is None or self._live < self._max:
                    self._live += 1
                    break  # create outside the lock
                # wait on the REMAINING time: each wakeup can lose the freed
                # value to another thread, and restarting the full timeout
                # every time would let a contended acquire block unboundedly
                remaining = None if deadline is None else deadline - _time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise TimeoutError("pool exhausted")
                if not self._cond.wait(timeout=remaining):
                    raise TimeoutError("pool exhausted")
        try:
            return self._factory()
        except BaseException:
            with self._cond:
                self._live -= 1
                self._cond.notify()
            raise

    def _return_value(self, value: T) -> None:
        if self._reset is not None:
            try:
                self._reset(value)
            except Exception:
                # a value that can't reset is dropped, freeing its slot —
                # loudly, or a flaky reset silently drains the pool to zero
                logger.warning(
                    "pool reset failed; dropping value %r", value, exc_info=True
                )
                with self._cond:
                    self._live -= 1
                    self._cond.notify()
                return
        with self._cond:
            self._free.append(value)
            self._cond.notify()

    @property
    def free_count(self) -> int:
        with self._cond:
            return len(self._free)

    @property
    def live_count(self) -> int:
        with self._cond:
            return self._live
