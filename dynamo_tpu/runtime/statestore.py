"""Self-hosted discovery/config state store with leases and prefix watches.

The control plane of the distributed runtime: capability parity with the
reference's etcd usage (lib/runtime/src/transports/etcd.rs:40-500 — leases
with keep-alive, atomic create-if-absent, prefix get/watch with Put/Delete
events), implemented as a lightweight asyncio TCP service speaking the framed
codec (runtime/codec.py) so deployments need no external etcd. Semantics:

- every key may be attached to a **lease**; lease expiry (missed keep-alives)
  or revoke deletes its keys and notifies watchers → dead workers vanish from
  the live set within a TTL, exactly like the reference's liveness model
  (SURVEY.md §5 failure detection).
- **watch(prefix)** streams Put/Delete events (optionally preceded by a
  snapshot of existing keys), the basis for client-side live endpoint sets
  and dynamic config.

Run standalone: ``python -m dynamo_tpu.runtime.statestore --port 37901``.
"""

from __future__ import annotations

import argparse
import asyncio
import base64
import itertools
import json
import logging
import time
import uuid
from dataclasses import dataclass, field
from typing import AsyncIterator, Dict, List, Optional, Tuple

from dynamo_tpu.runtime.codec import TwoPartMessage, read_frame, write_frame

logger = logging.getLogger(__name__)

DEFAULT_PORT = 37901
DEFAULT_LEASE_TTL = 10.0


@dataclass
class WatchEvent:
    type: str  # "put" | "delete"
    key: str
    value: bytes = b""


# =========================================================================
# server
# =========================================================================


@dataclass
class _Lease:
    lease_id: str
    ttl: float
    deadline: float
    keys: set = field(default_factory=set)


class _Watch:
    """A registered prefix watch with its own bounded send queue + sender task,
    so one stalled watcher can never block the server's mutation paths."""

    MAX_QUEUE = 4096

    def __init__(self, watch_id: str, prefix: str, writer: asyncio.StreamWriter):
        self.watch_id = watch_id
        self.prefix = prefix
        self.writer = writer
        self.queue: asyncio.Queue = asyncio.Queue(maxsize=self.MAX_QUEUE)
        self.task = asyncio.create_task(self._send_loop())
        self.dead = False

    def offer(self, frame: TwoPartMessage) -> None:
        try:
            self.queue.put_nowait(frame)
        except asyncio.QueueFull:
            # slow consumer: drop the watch (it would miss events anyway)
            self.dead = True
            self.task.cancel()

    async def _send_loop(self) -> None:
        try:
            while True:
                frame = await self.queue.get()
                await write_frame(self.writer, frame)
        except (ConnectionError, RuntimeError, asyncio.CancelledError):
            self.dead = True

    def close(self) -> None:
        self.task.cancel()


class StateStoreServer:
    def __init__(self, host: str = "127.0.0.1", port: int = DEFAULT_PORT):
        self.host = host
        self.port = port
        self._kv: Dict[str, Tuple[bytes, Optional[str]]] = {}  # key → (value, lease)
        self._leases: Dict[str, _Lease] = {}
        self._watches: Dict[str, _Watch] = {}
        self._server = None  # TrackedServer
        self._expiry_task: Optional[asyncio.Task] = None

    async def start(self) -> None:
        from dynamo_tpu.runtime.netutil import TrackedServer

        self._server = TrackedServer(self._handle, self.host, self.port)
        self.port = await self._server.start()
        self._expiry_task = asyncio.create_task(self._expire_loop())
        logger.info("statestore listening on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._expiry_task:
            self._expiry_task.cancel()
        if self._server:
            await self._server.stop()

    @property
    def url(self) -> str:
        return f"{self.host}:{self.port}"

    async def _expire_loop(self) -> None:
        while True:
            await asyncio.sleep(0.25)
            now = time.monotonic()
            for lease in [l for l in self._leases.values() if l.deadline < now]:
                logger.info("lease %s expired (%d keys)", lease.lease_id, len(lease.keys))
                await self._drop_lease(lease)

    async def _drop_lease(self, lease: _Lease) -> None:
        self._leases.pop(lease.lease_id, None)
        for key in list(lease.keys):
            await self._delete_key(key)

    async def _delete_key(self, key: str) -> bool:
        entry = self._kv.pop(key, None)
        if entry is None:
            return False
        _, lease_id = entry
        if lease_id and lease_id in self._leases:
            self._leases[lease_id].keys.discard(key)
        await self._notify(WatchEvent("delete", key))
        return True

    async def _put_key(self, key: str, value: bytes, lease_id: Optional[str]) -> None:
        old = self._kv.get(key)
        if old is not None and old[1] and old[1] in self._leases:
            self._leases[old[1]].keys.discard(key)
        self._kv[key] = (value, lease_id)
        if lease_id and lease_id in self._leases:
            self._leases[lease_id].keys.add(key)
        await self._notify(WatchEvent("put", key, value))

    async def _notify(self, event: WatchEvent) -> None:
        dead = []
        for w in list(self._watches.values()):
            if w.dead:
                dead.append(w.watch_id)
                continue
            if not event.key.startswith(w.prefix):
                continue
            w.offer(
                TwoPartMessage(
                    json.dumps(
                        {"push": "watch", "watch_id": w.watch_id,
                         "event": event.type, "key": event.key}
                    ).encode(),
                    event.value,
                )
            )
        for wid in dead:
            w = self._watches.pop(wid, None)
            if w:
                w.close()

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        conn_watches: List[str] = []
        try:
            while True:
                try:
                    frame = await read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    return
                req = json.loads(frame.header)
                reply_header, reply_body = await self._dispatch(
                    req, frame.body, writer, conn_watches
                )
                reply_header["id"] = req.get("id")
                await write_frame(
                    writer, TwoPartMessage(json.dumps(reply_header).encode(), reply_body)
                )
        finally:
            for wid in conn_watches:
                w = self._watches.pop(wid, None)
                if w:
                    w.close()
            writer.close()

    async def _dispatch(self, req, body, writer, conn_watches) -> Tuple[dict, bytes]:
        op = req.get("op")
        if op == "put":
            lease_id = req.get("lease")
            if lease_id and lease_id not in self._leases:
                return {"ok": False, "error": f"unknown lease {lease_id}"}, b""
            await self._put_key(req["key"], body, lease_id)
            return {"ok": True}, b""
        if op == "create":
            if req["key"] in self._kv:
                return {"ok": True, "created": False}, b""
            lease_id = req.get("lease")
            if lease_id and lease_id not in self._leases:
                return {"ok": False, "error": f"unknown lease {lease_id}"}, b""
            await self._put_key(req["key"], body, lease_id)
            return {"ok": True, "created": True}, b""
        if op == "get":
            entry = self._kv.get(req["key"])
            if entry is None:
                return {"ok": True, "found": False}, b""
            return {"ok": True, "found": True}, entry[0]
        if op == "get_prefix":
            items = [
                {"key": k, "value": base64.b64encode(v[0]).decode()}
                for k, v in sorted(self._kv.items())
                if k.startswith(req["prefix"])
            ]
            return {"ok": True}, json.dumps(items).encode()
        if op == "delete":
            deleted = await self._delete_key(req["key"])
            return {"ok": True, "deleted": deleted}, b""
        if op == "delete_prefix":
            keys = [k for k in self._kv if k.startswith(req["prefix"])]
            for k in keys:
                await self._delete_key(k)
            return {"ok": True, "count": len(keys)}, b""
        if op == "watch":
            watch_id = req.get("watch_id") or uuid.uuid4().hex
            w = _Watch(watch_id, req["prefix"], writer)
            self._watches[watch_id] = w
            conn_watches.append(watch_id)
            if req.get("include_existing"):
                for k, (v, _) in sorted(self._kv.items()):
                    if k.startswith(req["prefix"]):
                        w.offer(
                            TwoPartMessage(
                                json.dumps(
                                    {"push": "watch", "watch_id": watch_id,
                                     "event": "put", "key": k}
                                ).encode(),
                                v,
                            )
                        )
            return {"ok": True, "watch_id": watch_id}, b""
        if op == "unwatch":
            w = self._watches.pop(req["watch_id"], None)
            if w:
                w.close()
            return {"ok": True}, b""
        if op == "lease_grant":
            ttl = float(req.get("ttl", DEFAULT_LEASE_TTL))
            lease_id = uuid.uuid4().hex[:16]
            self._leases[lease_id] = _Lease(lease_id, ttl, time.monotonic() + ttl)
            return {"ok": True, "lease_id": lease_id, "ttl": ttl}, b""
        if op == "keepalive":
            lease = self._leases.get(req["lease_id"])
            if lease is None:
                return {"ok": False, "error": "unknown lease"}, b""
            lease.deadline = time.monotonic() + lease.ttl
            return {"ok": True}, b""
        if op == "revoke":
            lease = self._leases.get(req["lease_id"])
            if lease is not None:
                await self._drop_lease(lease)
            return {"ok": True}, b""
        return {"ok": False, "error": f"unknown op {op!r}"}, b""


# =========================================================================
# client
# =========================================================================


class Lease:
    """A granted lease with a background keep-alive heartbeat.

    Reference parity: Lease + keep-alive task (transports/etcd/lease.rs:19-117).
    """

    def __init__(self, client: "StateStoreClient", lease_id: str, ttl: float):
        self.client = client
        self.lease_id = lease_id
        self.ttl = ttl
        self._task: Optional[asyncio.Task] = None
        self.lost = asyncio.Event()

    def start_keepalive(self) -> None:
        self._task = asyncio.create_task(self._beat())

    async def _beat(self) -> None:
        try:
            while True:
                await asyncio.sleep(self.ttl / 3)
                try:
                    reply, _ = await self.client._call({"op": "keepalive", "lease_id": self.lease_id})
                    if not reply.get("ok"):
                        self.lost.set()
                        return
                except ConnectionError:
                    self.lost.set()
                    return
        except asyncio.CancelledError:
            pass

    async def revoke(self) -> None:
        if self._task:
            self._task.cancel()
        try:
            await self.client._call({"op": "revoke", "lease_id": self.lease_id})
        except ConnectionError:
            pass


class Watcher:
    """Async iterator of WatchEvents for a prefix."""

    def __init__(self, client: "StateStoreClient", watch_id: str):
        self.client = client
        self.watch_id = watch_id
        self.queue: asyncio.Queue = asyncio.Queue()

    def __aiter__(self) -> AsyncIterator[WatchEvent]:
        return self._iter()

    async def _iter(self) -> AsyncIterator[WatchEvent]:
        while True:
            ev = await self.queue.get()
            if ev is None:
                return
            yield ev

    async def cancel(self) -> None:
        self.client._watchers.pop(self.watch_id, None)
        try:
            await self.client._call({"op": "unwatch", "watch_id": self.watch_id})
        except ConnectionError:
            pass
        self.queue.put_nowait(None)


class StateStoreClient:
    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._ids = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        self._watchers: Dict[str, Watcher] = {}
        self._reader_task: Optional[asyncio.Task] = None
        self._send_lock = asyncio.Lock()

    @classmethod
    async def connect(cls, url: str) -> "StateStoreClient":
        host, _, port = url.rpartition(":")
        c = cls(host or "127.0.0.1", int(port))
        c._reader, c._writer = await asyncio.open_connection(c.host, c.port)
        c._reader_task = asyncio.create_task(c._read_loop())
        return c

    async def close(self) -> None:
        if self._reader_task:
            self._reader_task.cancel()
        if self._writer:
            self._writer.close()
        for w in self._watchers.values():
            w.queue.put_nowait(None)

    async def _read_loop(self) -> None:
        try:
            while True:
                frame = await read_frame(self._reader)
                h = json.loads(frame.header)
                if h.get("push") == "watch":
                    w = self._watchers.get(h["watch_id"])
                    if w is not None:
                        w.queue.put_nowait(WatchEvent(h["event"], h["key"], frame.body))
                    continue
                fut = self._pending.pop(h.get("id"), None)
                if fut is not None and not fut.done():
                    fut.set_result((h, frame.body))
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError):
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionError("statestore connection lost"))
            for w in self._watchers.values():
                w.queue.put_nowait(None)

    async def _call(self, req: dict, body: bytes = b"") -> Tuple[dict, bytes]:
        req_id = next(self._ids)
        req["id"] = req_id
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        async with self._send_lock:
            await write_frame(self._writer, TwoPartMessage(json.dumps(req).encode(), body))
        reply, rbody = await fut
        if not reply.get("ok"):
            raise RuntimeError(f"statestore error: {reply.get('error')}")
        return reply, rbody

    # -- public API ----------------------------------------------------------

    async def put(self, key: str, value: bytes, lease: Optional[Lease] = None) -> None:
        await self._call(
            {"op": "put", "key": key, "lease": lease.lease_id if lease else None}, value
        )

    async def create(self, key: str, value: bytes, lease: Optional[Lease] = None) -> bool:
        """Atomic create-if-absent (reference kv_create). True if created."""
        reply, _ = await self._call(
            {"op": "create", "key": key, "lease": lease.lease_id if lease else None},
            value,
        )
        return bool(reply.get("created"))

    async def get(self, key: str) -> Optional[bytes]:
        reply, body = await self._call({"op": "get", "key": key})
        return body if reply.get("found") else None

    async def get_prefix(self, prefix: str) -> Dict[str, bytes]:
        _, body = await self._call({"op": "get_prefix", "prefix": prefix})
        return {
            item["key"]: base64.b64decode(item["value"]) for item in json.loads(body)
        }

    async def delete(self, key: str) -> bool:
        reply, _ = await self._call({"op": "delete", "key": key})
        return bool(reply.get("deleted"))

    async def delete_prefix(self, prefix: str) -> int:
        reply, _ = await self._call({"op": "delete_prefix", "prefix": prefix})
        return int(reply.get("count", 0))

    async def grant_lease(self, ttl: float = DEFAULT_LEASE_TTL) -> Lease:
        reply, _ = await self._call({"op": "lease_grant", "ttl": ttl})
        lease = Lease(self, reply["lease_id"], reply["ttl"])
        lease.start_keepalive()
        return lease

    async def watch_prefix(self, prefix: str, include_existing: bool = True) -> Watcher:
        watch_id = uuid.uuid4().hex
        w = Watcher(self, watch_id)
        self._watchers[watch_id] = w
        await self._call(
            {"op": "watch", "prefix": prefix, "watch_id": watch_id,
             "include_existing": include_existing}
        )
        return w


def main() -> None:
    p = argparse.ArgumentParser(description="dynamo_tpu statestore server")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=DEFAULT_PORT)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    async def run():
        server = StateStoreServer(args.host, args.port)
        await server.start()
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":
    main()
