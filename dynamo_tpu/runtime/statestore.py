"""Self-hosted discovery/config state store with leases and prefix watches.

The control plane of the distributed runtime: capability parity with the
reference's etcd usage (lib/runtime/src/transports/etcd.rs:40-500 — leases
with keep-alive, atomic create-if-absent, prefix get/watch with Put/Delete
events), implemented as a lightweight asyncio TCP service speaking the framed
codec (runtime/codec.py) so deployments need no external etcd. Semantics:

- every key may be attached to a **lease**; lease expiry (missed keep-alives)
  or revoke deletes its keys and notifies watchers → dead workers vanish from
  the live set within a TTL, exactly like the reference's liveness model
  (SURVEY.md §5 failure detection).
- **watch(prefix)** streams Put/Delete events (optionally preceded by a
  snapshot of existing keys), the basis for client-side live endpoint sets
  and dynamic config.
- **durability** (``data_dir=``): mutations append to a JSONL write-ahead log,
  compacted into a snapshot once the log grows; a restarted server restores
  every key, registration and lease from disk (etcd-raft parity in spirit:
  a store bounce costs ≤ one lease TTL of disruption, not total state loss).
  Restored leases get one full TTL of grace — a client that survived the
  outage resumes keep-alives; one that died expires naturally.
- **client reconnect**: the client transparently re-dials a bounced server,
  retries in-flight calls, and re-establishes watches with a resync: missed
  deletions are synthesized by diffing the watch's live key set against the
  server's post-restart snapshot, so consumers keep a consistent view.

Run standalone: ``python -m dynamo_tpu.runtime.statestore --port 37901``.
"""

from __future__ import annotations

import argparse
import asyncio
import base64
import itertools
import json
import logging
import os
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Dict, List, Optional, Set, Tuple

from dynamo_tpu.runtime import control_plane, faults
from dynamo_tpu.runtime.codec import TwoPartMessage, read_frame, write_frame

logger = logging.getLogger(__name__)

DEFAULT_PORT = 37901
DEFAULT_LEASE_TTL = 10.0


@dataclass
class WatchEvent:
    type: str  # "put" | "delete"
    key: str
    value: bytes = b""
    # True only for deletes the CLIENT synthesized while adopting a resync
    # snapshot after a reconnect: the key is absent from the (possibly
    # freshly restarted, possibly empty) server, but nothing positively
    # observed its deletion. Stale-but-safe discovery consumers
    # (runtime/control_plane.py) treat these as "unconfirmed" and let the
    # RPC health probes arbitrate instead of dropping live workers.
    resync: bool = False


# =========================================================================
# server
# =========================================================================


@dataclass
class _Lease:
    lease_id: str
    ttl: float
    deadline: float
    keys: set = field(default_factory=set)


class _Watch:
    """A registered prefix watch with its own bounded send queue + sender task,
    so one stalled watcher can never block the server's mutation paths."""

    MAX_QUEUE = 4096

    def __init__(self, watch_id: str, prefix: str, writer: asyncio.StreamWriter):
        self.watch_id = watch_id
        self.prefix = prefix
        self.writer = writer
        self.queue: asyncio.Queue = asyncio.Queue(maxsize=self.MAX_QUEUE)
        self.task = asyncio.create_task(self._send_loop())
        self.dead = False

    def offer(self, frame: TwoPartMessage) -> None:
        try:
            self.queue.put_nowait(frame)
        except asyncio.QueueFull:
            # slow consumer: drop the watch (it would miss events anyway)
            self.dead = True
            self.task.cancel()

    async def _send_loop(self) -> None:
        try:
            while True:
                frame = await self.queue.get()
                await write_frame(self.writer, frame)
        except (ConnectionError, RuntimeError, asyncio.CancelledError):
            self.dead = True

    def close(self) -> None:
        self.task.cancel()


class StateStoreServer:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = DEFAULT_PORT,
        data_dir: Optional[str] = None,
        snapshot_every: int = 10_000,
    ):
        self.host = host
        self.port = port
        self._kv: Dict[str, Tuple[bytes, Optional[str]]] = {}  # key → (value, lease)
        self._leases: Dict[str, _Lease] = {}
        self._watches: Dict[str, _Watch] = {}
        # wal_tail subscribers (warm standbys): receive a state snapshot on
        # attach, then every WAL record live — regardless of whether this
        # server persists locally
        self._wal_tails: Dict[str, _Watch] = {}
        self._server = None  # TrackedServer
        self._expiry_task: Optional[asyncio.Task] = None
        self.data_dir = data_dir
        self.snapshot_every = snapshot_every
        self._wal = None  # append handle, open while serving
        self._wal_records = 0
        self._snapshot_task: Optional[asyncio.Task] = None
        # a promoting standby already holds replicated state + an open WAL;
        # start() must not clobber it with whatever is on disk
        self._skip_restore = False

    # -- persistence ---------------------------------------------------------

    @property
    def _snap_path(self) -> str:
        return os.path.join(self.data_dir, "snapshot.json")

    @property
    def _wal_path(self) -> str:
        return os.path.join(self.data_dir, "wal.jsonl")

    @property
    def _wal_old_path(self) -> str:
        return os.path.join(self.data_dir, "wal.old.jsonl")

    def _restore(self) -> None:
        """Load snapshot + replay WAL. Restored leases get a fresh TTL: a
        client that outlived the outage resumes keep-alives within ttl/3; a
        dead one expires naturally one TTL after restart."""
        now = time.monotonic()
        if os.path.exists(self._snap_path):
            try:
                with open(self._snap_path) as f:
                    snap = json.load(f)
            except (json.JSONDecodeError, OSError):
                logger.exception("corrupt snapshot at %s; starting empty", self._snap_path)
                snap = {"kv": {}, "leases": {}}
            for lid, ttl in snap.get("leases", {}).items():
                self._leases[lid] = _Lease(lid, float(ttl), now + float(ttl))
            for key, ent in snap.get("kv", {}).items():
                value = base64.b64decode(ent["v"])
                lease_id = ent.get("lease")
                if lease_id and lease_id not in self._leases:
                    continue  # lease vanished with an older incarnation
                self._kv[key] = (value, lease_id)
                if lease_id:
                    self._leases[lease_id].keys.add(key)
        n_replayed = 0
        # wal.old exists only if a crash interrupted an async compaction:
        # its records are ≤ the rotation point, the current WAL's are after
        # it — replay in that order (re-applying wal.old over a snapshot
        # that already contains it is order-preserving and converges)
        for path in (self._wal_old_path, self._wal_path):
            if not os.path.exists(path):
                continue
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        logger.warning("truncated WAL tail dropped (crash mid-append)")
                        break
                    self._replay(rec, now)
                    n_replayed += 1
        self._wal_records = n_replayed
        if self._kv or self._leases:
            logger.info(
                "restored %d keys, %d leases (%d WAL records)",
                len(self._kv), len(self._leases), n_replayed,
            )

    def _replay(self, rec: dict, now: float) -> None:
        op = rec.get("op")
        if op == "put":
            lease_id = rec.get("lease")
            if lease_id and lease_id not in self._leases:
                return
            old = self._kv.get(rec["key"])
            if old is not None and old[1] and old[1] in self._leases:
                self._leases[old[1]].keys.discard(rec["key"])
            self._kv[rec["key"]] = (base64.b64decode(rec["v"]), lease_id)
            if lease_id:
                self._leases[lease_id].keys.add(rec["key"])
        elif op == "delete":
            ent = self._kv.pop(rec["key"], None)
            if ent and ent[1] and ent[1] in self._leases:
                self._leases[ent[1]].keys.discard(rec["key"])
        elif op == "lease_grant":
            self._leases[rec["id"]] = _Lease(
                rec["id"], float(rec["ttl"]), now + float(rec["ttl"])
            )
        elif op == "lease_drop":
            lease = self._leases.pop(rec["id"], None)
            if lease:
                for key in lease.keys:
                    self._kv.pop(key, None)

    def _log(self, rec: dict) -> None:
        if self._wal_tails:
            frame = TwoPartMessage(
                json.dumps({"push": "wal", "rec": rec}).encode(), b""
            )
            dead = []
            for tid, w in self._wal_tails.items():
                if w.dead:
                    dead.append(tid)
                    continue
                w.offer(frame)
                if w.dead:  # offer overflowed: it missed this record
                    dead.append(tid)
            for tid in dead:
                w = self._wal_tails.pop(tid, None)
                if w:
                    w.close()
                    # close the CONNECTION too: a silently-dropped tail
                    # would leave the standby blocked in read_frame
                    # believing it is replicating — it must see the break
                    # and re-attach for a fresh snapshot
                    try:
                        w.writer.close()
                    except Exception:
                        pass
        if self._wal is None:
            return
        self._wal.write(json.dumps(rec) + "\n")
        self._wal.flush()
        self._wal_records += 1
        if (
            self._wal_records >= self.snapshot_every
            and (self._snapshot_task is None or self._snapshot_task.done())
        ):
            # rotate on-loop (cheap rename), serialize+fsync in a thread —
            # a big store must not stall calls/keepalives for the dump
            self._wal.close()
            if os.path.exists(self._wal_old_path):
                # a previous async snapshot failed and retained wal.old:
                # APPEND the current WAL to it (replay order preserved)
                # rather than clobbering those records
                with open(self._wal_old_path, "a") as dst, open(self._wal_path) as src:
                    dst.write(src.read())
                os.remove(self._wal_path)
            else:
                os.replace(self._wal_path, self._wal_old_path)
            self._wal = open(self._wal_path, "w")
            self._wal_records = 0
            snap = self._state_copy()
            self._snapshot_task = asyncio.get_running_loop().create_task(
                self._write_snapshot_async(snap)
            )

    def _state_copy(self) -> dict:
        """Point-in-time shallow copy (values are immutable bytes)."""
        return {
            "kv": dict(self._kv),
            "leases": {l.lease_id: l.ttl for l in self._leases.values()},
        }

    async def _write_snapshot_async(self, snap: dict) -> None:
        try:
            await asyncio.to_thread(self._dump_snapshot, snap)
            if os.path.exists(self._wal_old_path):
                os.remove(self._wal_old_path)
        except Exception:
            logger.exception("snapshot write failed; wal.old retained for replay")

    def _dump_snapshot(self, snap: dict) -> None:
        out = {
            "kv": {
                k: {"v": base64.b64encode(v).decode(), "lease": lease_id}
                for k, (v, lease_id) in snap["kv"].items()
            },
            "leases": snap["leases"],
        }
        tmp = f"{self._snap_path}.tmp.{os.getpid()}.{uuid.uuid4().hex[:8]}"
        with open(tmp, "w") as f:
            json.dump(out, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._snap_path)

    def _compact(self) -> None:
        """Synchronous snapshot + WAL truncate (graceful-stop path only)."""
        if self.data_dir is None:
            return
        self._dump_snapshot(self._state_copy())
        if self._wal is not None:
            self._wal.close()
        self._wal = open(self._wal_path, "w")  # truncate
        self._wal_records = 0
        if os.path.exists(self._wal_old_path):
            os.remove(self._wal_old_path)  # fully covered by this snapshot

    async def start(self) -> None:
        from dynamo_tpu.runtime.netutil import TrackedServer

        if self.data_dir is not None and not self._skip_restore:
            # startup path, runs once before serving — but off-loop, so a
            # large WAL replay or slow disk can't stall siblings sharing
            # this event loop (embedded deployments run several servers)

            def _restore_and_open():
                os.makedirs(self.data_dir, exist_ok=True)
                self._restore()
                return open(self._wal_path, "a")

            self._wal = await asyncio.to_thread(_restore_and_open)
        self._server = TrackedServer(self._handle, self.host, self.port)
        self.port = await self._server.start()
        self._expiry_task = asyncio.create_task(self._expire_loop())
        logger.info("statestore listening on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._expiry_task:
            self._expiry_task.cancel()
        if self._server:
            await self._server.stop()
        if self._snapshot_task is not None and not self._snapshot_task.done():
            # AWAIT, don't cancel: cancellation cannot stop an already-running
            # to_thread dump, which would finish later and overwrite the
            # fresh compacted snapshot below with its older state copy
            try:
                await self._snapshot_task
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("async snapshot failed during stop")
        if self._wal is not None:
            self._compact()  # graceful stop leaves a snapshot, empty WAL
            self._wal.close()
            self._wal = None

    @property
    def url(self) -> str:
        return f"{self.host}:{self.port}"

    async def _expire_loop(self) -> None:
        while True:
            await asyncio.sleep(0.25)
            now = time.monotonic()
            for lease in [l for l in self._leases.values() if l.deadline < now]:
                logger.info("lease %s expired (%d keys)", lease.lease_id, len(lease.keys))
                await self._drop_lease(lease)

    async def _drop_lease(self, lease: _Lease) -> None:
        self._leases.pop(lease.lease_id, None)
        for key in list(lease.keys):
            await self._delete_key(key, log=False)  # covered by lease_drop
        self._log({"op": "lease_drop", "id": lease.lease_id})

    async def _delete_key(self, key: str, log: bool = True) -> bool:
        entry = self._kv.pop(key, None)
        if entry is None:
            return False
        _, lease_id = entry
        if lease_id and lease_id in self._leases:
            self._leases[lease_id].keys.discard(key)
        if log:
            self._log({"op": "delete", "key": key})
        await self._notify(WatchEvent("delete", key))
        return True

    async def _put_key(self, key: str, value: bytes, lease_id: Optional[str]) -> None:
        old = self._kv.get(key)
        if old is not None and old[1] and old[1] in self._leases:
            self._leases[old[1]].keys.discard(key)
        self._kv[key] = (value, lease_id)
        if lease_id and lease_id in self._leases:
            self._leases[lease_id].keys.add(key)
        self._log({
            "op": "put", "key": key,
            "v": base64.b64encode(value).decode(), "lease": lease_id,
        })
        await self._notify(WatchEvent("put", key, value))

    async def _notify(self, event: WatchEvent) -> None:
        dead = []
        for w in list(self._watches.values()):
            if w.dead:
                dead.append(w.watch_id)
                continue
            if not event.key.startswith(w.prefix):
                continue
            w.offer(
                TwoPartMessage(
                    json.dumps(
                        {"push": "watch", "watch_id": w.watch_id,
                         "event": event.type, "key": event.key}
                    ).encode(),
                    event.value,
                )
            )
        for wid in dead:
            w = self._watches.pop(wid, None)
            if w:
                w.close()

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        conn_watches: List[_Watch] = []
        try:
            while True:
                try:
                    frame = await read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    return
                req = json.loads(frame.header)
                reply_header, reply_body = await self._dispatch(
                    req, frame.body, writer, conn_watches
                )
                reply_header["id"] = req.get("id")
                await write_frame(
                    writer, TwoPartMessage(json.dumps(reply_header).encode(), reply_body)
                )
        finally:
            for w in conn_watches:
                # identity check: a reconnecting client may have re-registered
                # the same watch_id on a NEW connection before this stale
                # handler unwound — popping by id alone would kill the live one
                if self._watches.get(w.watch_id) is w:
                    self._watches.pop(w.watch_id)
                if self._wal_tails.get(w.watch_id) is w:
                    self._wal_tails.pop(w.watch_id)
                w.close()
            writer.close()

    async def _dispatch(self, req, body, writer, conn_watches) -> Tuple[dict, bytes]:
        op = req.get("op")
        if op == "put":
            lease_id = req.get("lease")
            if lease_id and lease_id not in self._leases:
                return {"ok": False, "error": f"unknown lease {lease_id}"}, b""
            await self._put_key(req["key"], body, lease_id)
            return {"ok": True}, b""
        if op == "create":
            if req["key"] in self._kv:
                return {"ok": True, "created": False}, b""
            lease_id = req.get("lease")
            if lease_id and lease_id not in self._leases:
                return {"ok": False, "error": f"unknown lease {lease_id}"}, b""
            await self._put_key(req["key"], body, lease_id)
            return {"ok": True, "created": True}, b""
        if op == "get":
            entry = self._kv.get(req["key"])
            if entry is None:
                return {"ok": True, "found": False}, b""
            return {"ok": True, "found": True}, entry[0]
        if op == "get_prefix":
            items = [
                {"key": k, "value": base64.b64encode(v[0]).decode()}
                for k, v in sorted(self._kv.items())
                if k.startswith(req["prefix"])
            ]
            return {"ok": True}, json.dumps(items).encode()
        if op == "delete":
            deleted = await self._delete_key(req["key"])
            return {"ok": True, "deleted": deleted}, b""
        if op == "delete_prefix":
            keys = [k for k in self._kv if k.startswith(req["prefix"])]
            for k in keys:
                await self._delete_key(k)
            return {"ok": True, "count": len(keys)}, b""
        if op == "watch":
            watch_id = req.get("watch_id") or uuid.uuid4().hex
            old = self._watches.get(watch_id)
            if old is not None:
                old.close()  # same id re-registered (client resubscribe)
            w = _Watch(watch_id, req["prefix"], writer)
            self._watches[watch_id] = w
            conn_watches.append(w)
            if req.get("include_existing"):
                for k, (v, _) in sorted(self._kv.items()):
                    if k.startswith(req["prefix"]):
                        w.offer(
                            TwoPartMessage(
                                json.dumps(
                                    {"push": "watch", "watch_id": watch_id,
                                     "event": "put", "key": k}
                                ).encode(),
                                v,
                            )
                        )
                # end-of-snapshot marker: a reconnecting client diffs its
                # live key set against the snapshot at this point to
                # synthesize deletions that happened while it was away
                w.offer(
                    TwoPartMessage(
                        json.dumps(
                            {"push": "watch", "watch_id": watch_id,
                             "event": "sync", "key": ""}
                        ).encode(),
                        b"",
                    )
                )
            return {"ok": True, "watch_id": watch_id}, b""
        if op == "unwatch":
            w = self._watches.pop(req["watch_id"], None)
            if w:
                w.close()
            return {"ok": True}, b""
        if op == "wal_tail":
            # warm-standby attach: full state snapshot now, every WAL record
            # from here on (the raft-replication stand-in: one follower
            # tailing the leader's log — StandbyStateStore below)
            tail_id = req.get("tail_id") or uuid.uuid4().hex
            w = _Watch(tail_id, "", writer)
            self._wal_tails[tail_id] = w
            conn_watches.append(w)
            snap = {
                "kv": {
                    k: {"v": base64.b64encode(v).decode(), "lease": lid}
                    for k, (v, lid) in self._kv.items()
                },
                "leases": {l.lease_id: l.ttl for l in self._leases.values()},
            }
            w.offer(
                TwoPartMessage(
                    json.dumps({"push": "wal_snapshot"}).encode(),
                    json.dumps(snap).encode(),
                )
            )
            return {"ok": True, "tail_id": tail_id}, b""
        if op == "lease_grant":
            ttl = float(req.get("ttl", DEFAULT_LEASE_TTL))
            lease_id = uuid.uuid4().hex[:16]
            self._leases[lease_id] = _Lease(lease_id, ttl, time.monotonic() + ttl)
            self._log({"op": "lease_grant", "id": lease_id, "ttl": ttl})
            return {"ok": True, "lease_id": lease_id, "ttl": ttl}, b""
        if op == "keepalive":
            lease = self._leases.get(req["lease_id"])
            if lease is None:
                return {"ok": False, "error": "unknown lease"}, b""
            lease.deadline = time.monotonic() + lease.ttl
            return {"ok": True}, b""
        if op == "revoke":
            lease = self._leases.get(req["lease_id"])
            if lease is not None:
                await self._drop_lease(lease)
            return {"ok": True}, b""
        return {"ok": False, "error": f"unknown op {op!r}"}, b""


# =========================================================================
# client
# =========================================================================


class Lease:
    """A granted lease with a background keep-alive heartbeat.

    Reference parity: Lease + keep-alive task (transports/etcd/lease.rs:19-117).
    """

    def __init__(self, client: "StateStoreClient", lease_id: str, ttl: float):
        self.client = client
        self.lease_id = lease_id
        self.ttl = ttl
        self._task: Optional[asyncio.Task] = None
        self.lost = asyncio.Event()

    def start_keepalive(self) -> None:
        self._task = asyncio.create_task(self._beat())

    async def _beat(self) -> None:
        try:
            while True:
                await asyncio.sleep(self.ttl / 3)
                try:
                    reply, _ = await self.client._call({"op": "keepalive", "lease_id": self.lease_id})
                    if not reply.get("ok"):
                        self.lost.set()
                        return
                except ConnectionError:
                    self.lost.set()
                    return
                except RuntimeError:
                    # the server ANSWERED but rejected the keepalive
                    # ("unknown lease" — a store that restarted without our
                    # lease, e.g. empty data dir after a blackout): the
                    # lease is just as lost as on a dead connection, and
                    # the owner must re-register. _call raises this, so
                    # the not-ok branch above never fires in practice.
                    self.lost.set()
                    return
        except asyncio.CancelledError:
            pass

    async def revoke(self) -> None:
        if self._task:
            self._task.cancel()
        try:
            await self.client._call({"op": "revoke", "lease_id": self.lease_id})
        except ConnectionError:
            pass


# marks a key whose delete event was shed by a Watcher overflow: compares
# unequal to every real value hash, so the overflow resync re-emits the key
# as a synthetic delete (still gone) or a changed put (re-created)
_EVICTED = object()


class Watcher:
    """Async iterator of WatchEvents for a prefix.

    Tracks its own live view (key → value hash) so that after a server
    bounce the client can resubscribe and emit exactly the events the
    consumer missed: synthetic ``delete``s for keys that vanished, ``put``s
    only for keys that are new or whose value changed — consumers building
    incremental views (live endpoint sets, model registries) stay consistent
    without ever seeing the outage, and edge-triggered consumers
    (``include_existing=False``) never get spurious snapshot replays.

    The delivery queue is bounded (``MAX_QUEUE``). A consumer that stops
    draining while writers keep mutating sheds the *oldest* buffered event;
    because shed events would silently corrupt an incremental view, every
    eviction repairs the tracked view (so the shed event looks "unseen")
    and schedules a client-initiated re-watch — the same resync machinery
    that heals a server bounce then replays exactly what the consumer
    missed. Slow consumers trade a bounded snapshot replay for unbounded
    memory; ``dropped`` counts shed events for observability."""

    MAX_QUEUE = 4096

    def __init__(self, client: "StateStoreClient", watch_id: str, prefix: str = ""):
        self.client = client
        self.watch_id = watch_id
        self.prefix = prefix
        self.queue: asyncio.Queue = asyncio.Queue(maxsize=self.MAX_QUEUE)
        self.live: Dict[str, Any] = {}  # key → hash(value) (or _EVICTED)
        self._resync: Optional[Dict[str, int]] = None  # view forming during a snapshot
        self._silent_round = False  # prime `live` without emitting (include_existing=False)
        self.dropped = 0
        self._overflow = False  # an eviction happened; a resync is owed
        self._resync_task: Optional[asyncio.Task] = None  # strong ref

    @property
    def live_keys(self) -> Set[str]:
        return set(self.live)

    def _offer(self, ev: WatchEvent) -> None:
        """Enqueue for the consumer, shedding oldest on overflow.

        Each shed event repairs the tracked view so the overflow resync
        re-emits what the consumer missed: a shed put forgets the key
        (resync sees it as new-or-changed); a shed delete resurrects it
        with :data:`_EVICTED` (resync emits a synthetic delete, or a
        changed put if the key was re-created meanwhile)."""
        while self.queue.full():
            try:
                old = self.queue.get_nowait()
            except asyncio.QueueEmpty:  # pragma: no cover - racy full()
                break
            if old is None:
                # never shed the end-of-stream sentinel: put it back and
                # drop the new event instead (the stream is over anyway)
                self.queue.put_nowait(None)
                self.dropped += 1
                return
            self.dropped += 1
            self._overflow = True
            for view in (self.live, self._resync):
                if view is None:
                    continue
                if old.event == "put":
                    view.pop(old.key, None)
                else:
                    view[old.key] = _EVICTED
        try:
            self.queue.put_nowait(ev)
        except asyncio.QueueFull:  # pragma: no cover - single-threaded loop
            self.dropped += 1
        if self._overflow and self._resync is None:
            # not mid-snapshot: start the repair resync now (mid-snapshot
            # overflows are picked up by the sync handler instead, so two
            # replays never interleave on one watch_id)
            self._schedule_resync()

    def _close(self) -> None:
        """Wake the consumer with the end-of-stream sentinel; on a full
        queue one event is shed so the sentinel always fits."""
        if self._resync_task is not None:
            self._resync_task.cancel()
        while True:
            try:
                self.queue.put_nowait(None)
                return
            except asyncio.QueueFull:
                try:
                    self.queue.get_nowait()
                    self.dropped += 1
                except asyncio.QueueEmpty:  # pragma: no cover
                    pass

    def _schedule_resync(self) -> None:
        if self._resync_task is not None and not self._resync_task.done():
            return
        self._resync_task = asyncio.get_running_loop().create_task(
            self._overflow_resync()
        )

    async def _overflow_resync(self) -> None:
        """Client-initiated re-watch after an overflow: the server treats a
        ``watch`` with an existing watch_id as an atomic re-subscribe (old
        watch closed, snapshot + sync replayed), and the normal resync
        diffing then emits exactly the events the shed made the consumer
        miss."""
        self._overflow = False
        self._resync = {}
        try:
            await self.client._call(
                {"op": "watch", "prefix": self.prefix,
                 "watch_id": self.watch_id, "include_existing": True}
            )
        except (ConnectionError, RuntimeError):
            # connection died: the reconnect path owns re-establishing the
            # watch (with its own resync), which supersedes this one
            self._resync = None

    def __aiter__(self) -> AsyncIterator[WatchEvent]:
        return self._iter()

    async def _iter(self) -> AsyncIterator[WatchEvent]:
        while True:
            ev = await self.queue.get()
            if ev is None:
                return
            yield ev

    async def cancel(self) -> None:
        self.client._watchers.pop(self.watch_id, None)
        try:
            await self.client._call({"op": "unwatch", "watch_id": self.watch_id})
        except ConnectionError:
            pass
        self._close()


class StateStoreClient:
    def __init__(
        self,
        host: str,
        port: int,
        reconnect: bool = True,
        reconnect_timeout: float = 30.0,
    ):
        self.host = host
        self.port = port
        self.reconnect = reconnect
        self.reconnect_timeout = reconnect_timeout
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._ids = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        self._watchers: Dict[str, Watcher] = {}
        self._reader_task: Optional[asyncio.Task] = None
        self._send_lock = asyncio.Lock()
        self._closed = False
        self._connected = asyncio.Event()
        self._ever_connected = False
        # monotonic time THIS client last lost its connection (None =
        # never): recovery paths use it to tell outage-caused lease loss
        # from a plain expiry without consulting process-global state
        self.last_disconnect_at: Optional[float] = None
        self._reconnect_task: Optional[asyncio.Task] = None  # strong ref

    @classmethod
    async def connect(
        cls,
        url: str,
        reconnect: bool = True,
        reconnect_timeout: float = 30.0,
    ) -> "StateStoreClient":
        host, _, port = url.rpartition(":")
        c = cls(host or "127.0.0.1", int(port), reconnect, reconnect_timeout)
        await c._dial()
        return c

    @classmethod
    async def connect_lazy(
        cls,
        url: str,
        reconnect: bool = True,
        reconnect_timeout: float = 30.0,
    ) -> "StateStoreClient":
        """A client for a statestore that may be DOWN right now (cache-mode
        cold start, runtime/control_plane.py): one dial is attempted; on
        failure the client exists in disconnected, fail-fast state — calls
        raise ``ConnectionError`` immediately instead of blocking out the
        reconnect window, so the runtime's own recovery loops (which
        re-dial via ``reconnect_store``) converge as soon as the store
        returns."""
        host, _, port = url.rpartition(":")
        c = cls(host or "127.0.0.1", int(port), reconnect, reconnect_timeout)
        try:
            await c._dial()
        except OSError:
            c.last_disconnect_at = time.monotonic()
            control_plane.note_store(False)
        return c

    @property
    def connected(self) -> bool:
        return self._connected.is_set()

    async def _dial(self) -> None:
        self._reader, self._writer = await faults.open_connection(
            self.host, self.port, plane="statestore"
        )
        self._connected.set()
        self._ever_connected = True
        self._reader_task = asyncio.create_task(self._read_loop())
        control_plane.note_store(True)

    async def close(self) -> None:
        self._closed = True
        if self._reader_task:
            self._reader_task.cancel()
        if self._reconnect_task is not None:
            self._reconnect_task.cancel()
        # wake any _call blocked in _connected.wait(): it re-checks _closed
        # via the ConnectionError path instead of sitting out the full
        # reconnect_timeout after shutdown
        self._connected.set()
        if self._writer:
            self._writer.close()
        for w in self._watchers.values():
            w._close()

    async def _read_loop(self) -> None:
        try:
            while True:
                frame = await read_frame(self._reader)
                h = json.loads(frame.header)
                if h.get("push") == "watch":
                    self._on_watch_push(h, frame.body)
                    continue
                fut = self._pending.pop(h.get("id"), None)
                if fut is not None and not fut.done():
                    fut.set_result((h, frame.body))
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError):
            self._connected.clear()
            if not self._closed:
                self.last_disconnect_at = time.monotonic()
                control_plane.note_store(False)
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionError("statestore connection lost"))
            self._pending.clear()
            if self._closed or not self.reconnect:
                for w in self._watchers.values():
                    w._close()
            else:
                # keep a strong reference: asyncio only weakly refs tasks and
                # a GC'd reconnect task would strand the client forever
                self._reconnect_task = asyncio.get_running_loop().create_task(
                    self._reconnect_loop()
                )

    def _on_watch_push(self, h: dict, body: bytes) -> None:
        w = self._watchers.get(h["watch_id"])
        if w is None:
            return
        ev = h["event"]
        if ev == "sync":
            # end of a (re)subscription snapshot: emit deletes for keys that
            # vanished while we were away, then adopt the snapshot view
            if w._resync is not None:
                if not w._silent_round:
                    for k in sorted(set(w.live) - set(w._resync)):
                        w._offer(
                            WatchEvent("delete", k, resync=True)
                        )
                w.live = dict(w._resync)
                w._resync = None
                w._silent_round = False
                if w._overflow:
                    # events were shed while this snapshot replayed: the
                    # repaired view needs one more replay to converge
                    w._schedule_resync()
            return
        if ev == "put":
            hv = hash(body)
            if w._resync is not None:
                # snapshot entry: emit only if new-or-changed vs the view
                # the consumer last saw (suppresses no-op replays on resync)
                changed = w.live.get(h["key"]) != hv
                w._resync[h["key"]] = hv
                if w._silent_round or not changed:
                    return
            else:
                w.live[h["key"]] = hv
        elif ev == "delete":
            w.live.pop(h["key"], None)
        w._offer(WatchEvent(ev, h["key"], body))

    async def _reconnect_loop(self) -> None:
        """Re-dial a bounced server with backoff, then re-establish every
        watch with a resync snapshot. Gives up (ending all watchers) after
        ``reconnect_timeout``."""
        deadline = time.monotonic() + self.reconnect_timeout
        delay = 0.05
        while not self._closed:
            try:
                await self._dial()
            except OSError:
                if time.monotonic() > deadline:
                    logger.warning(
                        "statestore unreachable for %.0fs; giving up",
                        self.reconnect_timeout,
                    )
                    for w in self._watchers.values():
                        w._close()
                    return
                await asyncio.sleep(delay)
                delay = min(delay * 2, 1.0)
                continue
            logger.info("statestore reconnected; resyncing %d watches", len(self._watchers))
            for w in list(self._watchers.values()):
                w._resync = {}
                try:
                    await self._call_once(
                        {"op": "watch", "prefix": w.prefix,
                         "watch_id": w.watch_id, "include_existing": True}
                    )
                except (ConnectionError, RuntimeError):
                    break  # connection dropped again: read loop re-triggers us
            return

    async def _call_once(self, req: dict, body: bytes = b"") -> Tuple[dict, bytes]:
        req_id = next(self._ids)
        req["id"] = req_id
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        try:
            async with self._send_lock:
                await write_frame(
                    self._writer, TwoPartMessage(json.dumps(req).encode(), body)
                )
            reply, rbody = await fut
        except (ConnectionError, OSError) as e:
            self._pending.pop(req_id, None)
            raise ConnectionError(str(e)) from e
        if not reply.get("ok"):
            raise RuntimeError(f"statestore error: {reply.get('error')}")
        return reply, rbody

    async def _call(self, req: dict, body: bytes = b"") -> Tuple[dict, bytes]:
        """Issue a call, transparently retrying across a server bounce. Every
        op is idempotent on retry (put/delete are, create reports
        created=False, a double lease_grant merely orphans a lease that
        expires on its own)."""
        deadline = time.monotonic() + self.reconnect_timeout
        while True:
            if not self._connected.is_set():
                if self._closed or not self.reconnect:
                    raise ConnectionError("statestore client closed")
                if not self._ever_connected:
                    # lazy client that never reached the store (cache-mode
                    # cold start): fail fast so recovery loops re-dial via
                    # reconnect_store instead of blocking a full reconnect
                    # window per call
                    raise ConnectionError(
                        f"statestore {self.host}:{self.port} unreachable"
                    )
                budget = deadline - time.monotonic()
                if budget <= 0:
                    raise ConnectionError("statestore unreachable")
                try:
                    await asyncio.wait_for(self._connected.wait(), budget)
                except asyncio.TimeoutError:
                    raise ConnectionError("statestore unreachable") from None
            try:
                return await self._call_once(dict(req), body)
            except ConnectionError:
                if self._closed or not self.reconnect:
                    raise
                if time.monotonic() > deadline:
                    raise
                await asyncio.sleep(0.05)  # let the read loop notice the drop

    # -- public API ----------------------------------------------------------

    async def put(self, key: str, value: bytes, lease: Optional[Lease] = None) -> None:
        await self._call(
            {"op": "put", "key": key, "lease": lease.lease_id if lease else None}, value
        )

    async def create(self, key: str, value: bytes, lease: Optional[Lease] = None) -> bool:
        """Atomic create-if-absent (reference kv_create). True if created."""
        reply, _ = await self._call(
            {"op": "create", "key": key, "lease": lease.lease_id if lease else None},
            value,
        )
        return bool(reply.get("created"))

    async def get(self, key: str) -> Optional[bytes]:
        reply, body = await self._call({"op": "get", "key": key})
        return body if reply.get("found") else None

    async def get_prefix(self, prefix: str) -> Dict[str, bytes]:
        _, body = await self._call({"op": "get_prefix", "prefix": prefix})
        return {
            item["key"]: base64.b64decode(item["value"]) for item in json.loads(body)
        }

    async def delete(self, key: str) -> bool:
        reply, _ = await self._call({"op": "delete", "key": key})
        return bool(reply.get("deleted"))

    async def delete_prefix(self, prefix: str) -> int:
        reply, _ = await self._call({"op": "delete_prefix", "prefix": prefix})
        return int(reply.get("count", 0))

    async def grant_lease(self, ttl: float = DEFAULT_LEASE_TTL) -> Lease:
        reply, _ = await self._call({"op": "lease_grant", "ttl": ttl})
        lease = Lease(self, reply["lease_id"], reply["ttl"])
        lease.start_keepalive()
        return lease

    async def watch_prefix(self, prefix: str, include_existing: bool = True) -> Watcher:
        watch_id = uuid.uuid4().hex
        w = Watcher(self, watch_id, prefix)
        # always take the server-side snapshot to prime the watcher's live
        # view (needed for correct delete-diff resyncs after a bounce);
        # include_existing=False consumers get a silent priming round so
        # their edge-triggered contract holds
        w._resync = {}
        w._silent_round = not include_existing
        self._watchers[watch_id] = w
        await self._call(
            {"op": "watch", "prefix": prefix, "watch_id": watch_id,
             "include_existing": True}
        )
        return w


class StandbyStateStore:
    """Warm standby: tails the primary's WAL stream and takes over its
    address on primary loss.

    The raft stand-in for the self-hosted store (reference: etcd,
    lib/runtime/src/transports/etcd.rs:40-500): ONE follower replicates the
    leader's log (snapshot on attach + live records), and on leader death
    binds the leader's host:port and serves. Clients already reconnect with
    backoff to the same address and resync watches, so the failover is
    transparent to them; promoted leases get a fresh TTL (same grace as a
    restart — live owners resume keep-alives within ttl/3, dead ones expire
    one TTL later).

    Split-brain note (documented blast radius): there is no quorum — the
    operator must not run the old primary again after a promotion without
    wiping its data dir. The standby only promotes once its primary
    CONNECTION breaks, and binding the primary's port fails fast if the
    primary is actually still alive.
    """

    def __init__(
        self,
        primary_url: str,
        host: str,
        port: int,
        data_dir: Optional[str] = None,
        promote_after: float = 3.0,
    ):
        self.primary_url = primary_url
        # grace window: a broken tail first RE-ATTACHES (fresh snapshot) if
        # the primary is still reachable — a transient TCP reset or a
        # primary upgrade-restart must not trigger an irreversible
        # promotion (split brain if the standby is on another machine)
        self.promote_after = promote_after
        # the server we will become; not listening until promotion
        self.server = StateStoreServer(host, port, data_dir=data_dir)
        if data_dir is not None:
            # persistence is owned HERE: open the WAL now so replicated
            # records land on disk, and keep start() from re-reading stale
            # disk state over the replica
            os.makedirs(data_dir, exist_ok=True)
            self.server._wal = open(self.server._wal_path, "a")
        self.server._skip_restore = True
        self.promoted = asyncio.Event()
        self._synced = False

    async def run(self) -> None:
        """Replicate until the primary dies, then promote and serve.

        Returns once promoted (the server keeps serving; stop via
        ``self.server.stop()``)."""
        host, _, port = self.primary_url.rpartition(":")
        host = host or "127.0.0.1"
        port = int(port)
        down_since: Optional[float] = None
        while not self.promoted.is_set():
            try:
                reader, writer = await faults.open_connection(
                    host, port, plane="statestore"
                )
            except OSError:
                now = time.monotonic()
                if down_since is None:
                    down_since = now
                if self._synced and now - down_since >= self.promote_after:
                    # primary unreachable beyond the grace window: take over
                    await self._promote()
                    return
                await asyncio.sleep(0.2)
                continue
            down_since = None  # reachable again: replication resumes
            try:
                await write_frame(
                    writer,
                    TwoPartMessage(
                        json.dumps({"op": "wal_tail", "id": 1}).encode(), b""
                    ),
                )
                while True:
                    frame = await read_frame(reader)
                    h = json.loads(frame.header)
                    if h.get("push") == "wal_snapshot":
                        self._apply_snapshot(json.loads(frame.body))
                        self._synced = True
                    elif h.get("push") == "wal":
                        self.server._replay(h["rec"], time.monotonic())
                        self.server._log(h["rec"])  # local durability
            except (asyncio.IncompleteReadError, ConnectionError, OSError):
                # tail broke: try to RE-ATTACH (the primary may be alive —
                # slow-tail drop, rolling restart, network blip); promotion
                # happens only after promote_after seconds of unreachability
                writer.close()
                down_since = time.monotonic()
                await asyncio.sleep(0.1)

    def _apply_snapshot(self, snap: dict) -> None:
        now = time.monotonic()
        self.server._kv.clear()
        self.server._leases.clear()
        for lid, ttl in snap.get("leases", {}).items():
            self.server._leases[lid] = _Lease(lid, float(ttl), now + float(ttl))
        for key, ent in snap.get("kv", {}).items():
            value = base64.b64decode(ent["v"])
            lease_id = ent.get("lease")
            if lease_id and lease_id not in self.server._leases:
                continue
            self.server._kv[key] = (value, lease_id)
            if lease_id:
                self.server._leases[lease_id].keys.add(key)
        if self.server._wal is not None:
            # local disk now mirrors the attach point: snapshot + empty WAL
            self.server._compact()

    async def _promote(self) -> None:
        now = time.monotonic()
        for lease in self.server._leases.values():
            # fresh TTL: live owners resume keep-alives, dead ones expire
            lease.deadline = now + lease.ttl
        # the primary's port may linger in TIME_WAIT or the primary may be
        # mid-death: retry the bind briefly
        last: Optional[Exception] = None
        for _ in range(50):
            try:
                await self.server.start()
                break
            except OSError as e:
                last = e
                await asyncio.sleep(0.1)
        else:
            raise RuntimeError(f"standby could not bind primary address: {last}")
        self.promoted.set()
        logger.warning(
            "standby PROMOTED: serving %d keys on %s",
            len(self.server._kv), self.server.url,
        )


def main() -> None:
    p = argparse.ArgumentParser(description="dynamo_tpu statestore server")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=DEFAULT_PORT)
    p.add_argument(
        "--data-dir", default=None,
        help="persist state (snapshot + WAL) here; restart restores it",
    )
    p.add_argument(
        "--standby-of", default=None, metavar="HOST:PORT",
        help="run as a warm standby of this primary: replicate its WAL "
             "stream, take over --host:--port on primary loss",
    )
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    async def run():
        if args.standby_of:
            standby = StandbyStateStore(
                args.standby_of, args.host, args.port, data_dir=args.data_dir
            )
            await standby.run()
        else:
            server = StateStoreServer(args.host, args.port, data_dir=args.data_dir)
            await server.start()
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":
    main()
