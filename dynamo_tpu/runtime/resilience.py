"""Request-path fault tolerance: deadlines, retry budgets, circuit breaking.

The direct-dial design (statestore hands the client a worker address, the
client dials it — runtime/rpc.py) is one hop faster than the reference's
broker-mediated path, but it also means the client is the only party that can
absorb worker churn: there is no NATS to re-queue a request whose chosen
instance died between watch events. This module is that absorption layer:

- :class:`ResiliencePolicy` — the per-client knob bundle: total request
  deadline, connect timeout, inter-item stall bound, pre-first-token retry
  budget with exponential backoff + jitter, and circuit-breaker tuning.
- :class:`Deadline` — a monotonic time budget threaded from the HTTP edge
  through ``EndpointClient`` into the RPC header, so workers can shed
  requests that expired in flight.
- :class:`CircuitBreaker` — per-instance closed → open → half-open state
  machine; repeatedly-failing instances are ejected from routing until a
  half-open probe proves them healthy again.

Semantics contract (docs/resilience.md): failover is only legal while no
response item has been delivered to the caller — after the first token the
request is pinned to its instance and failures surface in-band.

Reference analogue: the reference leans on NATS redelivery + etcd liveness
(SURVEY.md §5); this is the equivalent capability re-designed for the
direct-dial data plane.
"""

from __future__ import annotations

import os
import random
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

# Canonical message prefix for deadline errors crossing process boundaries as
# Annotated error envelopes; the HTTP edge maps it to 504 vs the generic 502.
DEADLINE_ERROR = "deadline exceeded"


class DeadlineExceeded(TimeoutError):
    """The request's total time budget ran out (connect, queueing, or an
    inter-item gap). Not retryable: the budget is already spent."""


class RetryableRpcError(ConnectionError):
    """A worker rejected the request before streaming anything (draining,
    endpoint briefly unregistered) — safe to fail over to another instance."""


class WorkerStalled(ConnectionError):
    """The worker accepted the request but exceeded the inter-item stall
    bound without producing anything — treated like a dead connection."""


class NoHealthyInstances(RuntimeError):
    """No live instance is available to try (empty set, or every breaker
    open and the last-ditch pass also failed)."""


class AllInstancesFailed(ConnectionError):
    """The pre-first-token retry budget is exhausted; carries the last
    underlying failure as ``__cause__``."""


def _monotonic() -> float:
    return time.monotonic()


class Deadline:
    """A monotonic time budget. ``budget=None`` means unlimited."""

    __slots__ = ("_t0", "_budget", "_clock")

    def __init__(self, budget: Optional[float], clock: Callable[[], float] = _monotonic):
        self._clock = clock
        self._t0 = clock()
        self._budget = budget

    @classmethod
    def after(cls, budget: Optional[float],
              clock: Callable[[], float] = _monotonic) -> "Deadline":
        return cls(budget, clock)

    @property
    def budget(self) -> Optional[float]:
        return self._budget

    def remaining(self) -> Optional[float]:
        """Seconds left (may be ≤ 0); None when unlimited."""
        if self._budget is None:
            return None
        return self._budget - (self._clock() - self._t0)

    @property
    def expired(self) -> bool:
        rem = self.remaining()
        return rem is not None and rem <= 0

    def bound(self, timeout: Optional[float]) -> Optional[float]:
        """Combine with another timeout: the tighter of the two (None = no
        bound from that side)."""
        rem = self.remaining()
        if rem is None:
            return timeout
        if timeout is None:
            return max(rem, 0.0)
        return max(min(rem, timeout), 0.0)

    def check(self, what: str = "") -> None:
        if self.expired:
            raise DeadlineExceeded(
                f"{DEADLINE_ERROR}{f' ({what})' if what else ''}: "
                f"budget {self._budget:.3f}s spent"
            )


def _env_float(name: str, default: Optional[float]) -> Optional[float]:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        v = float(raw)
    except ValueError:
        return default
    return None if v <= 0 else v


def _env_int(name: str, default: int) -> int:
    """Count knobs (attempts, breaker threshold): malformed, zero, or
    negative values clamp to the default — "0 retries" or "-1 failures to
    trip" are misconfigurations, not policies (same contract as the
    ``DYN_TPU_ADMIT_*`` parsers in runtime/admission.py)."""
    try:
        v = int(os.environ.get(name, default))
    except ValueError:
        return default
    return v if v > 0 else default


@dataclass
class ResiliencePolicy:
    """Per-client resilience knobs. The defaults keep today's behavior for
    patient callers (no total deadline) while bounding the failure modes
    that used to hang or error: connects time out, stalled workers are cut,
    and pre-first-token failures fail over instead of surfacing.

    ``request_timeout``      total budget for the request (None = unlimited);
                             propagated to the worker in the RPC header.
    ``connect_timeout``      per-attempt dial bound.
    ``inter_item_timeout``   max gap between stream items (None = unlimited);
                             also bounds time-to-first-token.
    ``max_attempts``         pre-first-token tries across instances.
    ``backoff_*`` / ``jitter`` exponential backoff between attempts;
                             jitter is a 0..jitter fraction added on top.
    ``breaker_*``            consecutive-failure threshold, open-state
                             cooldown, and half-open probe admission count.
    ``seed``                 fixes the jitter RNG (tests / reproducibility).
    """

    request_timeout: Optional[float] = None
    connect_timeout: float = 5.0
    inter_item_timeout: Optional[float] = None
    max_attempts: int = 3
    backoff_base: float = 0.05
    backoff_multiplier: float = 2.0
    backoff_max: float = 2.0
    jitter: float = 0.5
    breaker_threshold: int = 5
    breaker_cooldown: float = 5.0
    breaker_half_open_probes: int = 1
    seed: Optional[int] = None

    def rng(self) -> random.Random:
        return random.Random(self.seed)

    def backoff(self, attempt: int, rng: Optional[random.Random] = None) -> float:
        """Delay before retry ``attempt`` (1-based): exponential with jitter."""
        base = min(
            self.backoff_base * (self.backoff_multiplier ** max(attempt - 1, 0)),
            self.backoff_max,
        )
        if self.jitter <= 0:
            return base
        r = (rng or random).random()
        return base * (1.0 + self.jitter * r)

    @classmethod
    def from_env(cls, prefix: str = "DYN_TPU_") -> "ResiliencePolicy":
        """Build a policy from ``DYN_TPU_REQUEST_TIMEOUT`` etc. Unset or
        malformed values keep the defaults. ``0`` disables the *optional*
        timeouts (``REQUEST_TIMEOUT``, ``INTER_ITEM_TIMEOUT`` → unlimited);
        the knobs that must stay positive (``CONNECT_TIMEOUT``,
        ``BREAKER_COOLDOWN``) fall back to their defaults when ≤ 0."""
        d = cls()
        return cls(
            request_timeout=_env_float(prefix + "REQUEST_TIMEOUT", d.request_timeout),
            connect_timeout=_env_float(prefix + "CONNECT_TIMEOUT", d.connect_timeout)
            or d.connect_timeout,
            inter_item_timeout=_env_float(
                prefix + "INTER_ITEM_TIMEOUT", d.inter_item_timeout
            ),
            max_attempts=max(1, _env_int(prefix + "MAX_ATTEMPTS", d.max_attempts)),
            breaker_threshold=max(
                1, _env_int(prefix + "BREAKER_THRESHOLD", d.breaker_threshold)
            ),
            breaker_cooldown=_env_float(
                prefix + "BREAKER_COOLDOWN", d.breaker_cooldown
            )
            or d.breaker_cooldown,
        )


# Breaker states (plain strings so they read well in logs/metrics).
CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


@dataclass
class _BreakerSlot:
    state: str = CLOSED
    consecutive_failures: int = 0
    opened_at: float = 0.0
    half_open_inflight: int = 0


class CircuitBreaker:
    """Per-key (endpoint instance) circuit breaker.

    closed    — all traffic admitted; ``threshold`` consecutive failures
                trip the breaker open.
    open      — no traffic for ``cooldown`` seconds.
    half_open — up to ``half_open_probes`` concurrent probes admitted;
                one success closes the breaker, one failure re-opens it
                (restarting the cooldown).
    """

    def __init__(
        self,
        threshold: int = 5,
        cooldown: float = 5.0,
        half_open_probes: int = 1,
        clock: Callable[[], float] = _monotonic,
    ):
        self.threshold = max(1, threshold)
        self.cooldown = cooldown
        self.half_open_probes = max(1, half_open_probes)
        self._clock = clock
        self._slots: Dict[str, _BreakerSlot] = {}

    def _slot(self, key: str) -> _BreakerSlot:
        slot = self._slots.get(key)
        if slot is None:
            slot = self._slots[key] = _BreakerSlot()
        return slot

    def state(self, key: str) -> str:
        slot = self._slots.get(key)
        if slot is None:
            return CLOSED
        if slot.state == OPEN and self._clock() - slot.opened_at >= self.cooldown:
            return HALF_OPEN
        return slot.state

    def available(self, key: str) -> bool:
        """Pure check: may a request be routed to ``key`` right now? Safe to
        call while *filtering* candidates — it never consumes a probe slot
        (that's :meth:`acquire`, called once for the chosen instance)."""
        st = self.state(key)
        if st == CLOSED:
            return True
        if st == OPEN:
            return False
        slot = self._slots[key]
        return slot.half_open_inflight < self.half_open_probes

    def acquire(self, key: str) -> None:
        """Commit a routing decision to ``key``: in half-open state this
        consumes a probe slot (released by record_success/record_failure)."""
        slot = self._slots.get(key)
        if slot is None or slot.state == CLOSED:
            return
        if self.state(key) == HALF_OPEN:
            if slot.state == OPEN:  # cooldown just elapsed: materialize
                slot.state = HALF_OPEN
                slot.half_open_inflight = 0
            slot.half_open_inflight += 1

    def release(self, key: str) -> None:
        """Un-commit an :meth:`acquire` that resolved with *neither* success
        nor failure (deadline expiry, abandoned stream, application error):
        the half-open probe slot must return to the pool or the instance
        stays ejected forever."""
        slot = self._slots.get(key)
        if slot is not None and slot.half_open_inflight > 0:
            slot.half_open_inflight -= 1

    def record_success(self, key: str) -> None:
        slot = self._slots.get(key)
        if slot is None:
            return
        slot.state = CLOSED
        slot.consecutive_failures = 0
        slot.half_open_inflight = 0

    def record_failure(self, key: str) -> None:
        slot = self._slot(key)
        if slot.state == HALF_OPEN:
            # failed probe: straight back to open, cooldown restarts
            slot.state = OPEN
            slot.opened_at = self._clock()
            slot.half_open_inflight = 0
            return
        slot.consecutive_failures += 1
        if slot.consecutive_failures >= self.threshold and slot.state != OPEN:
            slot.state = OPEN
            slot.opened_at = self._clock()

    def forget(self, key: str) -> None:
        """Drop state for an instance that left the live set."""
        self._slots.pop(key, None)

    def prune(self, live_keys) -> None:
        """Drop state for every instance not in ``live_keys`` — leak
        containment for recovery paths that replace the live set wholesale
        without per-instance delete events."""
        for key in [k for k in self._slots if k not in live_keys]:
            del self._slots[key]

    def snapshot(self) -> Dict[str, str]:
        return {k: self.state(k) for k in self._slots}
