"""Request-path fault tolerance: deadlines, retry budgets, circuit breaking.

The direct-dial design (statestore hands the client a worker address, the
client dials it — runtime/rpc.py) is one hop faster than the reference's
broker-mediated path, but it also means the client is the only party that can
absorb worker churn: there is no NATS to re-queue a request whose chosen
instance died between watch events. This module is that absorption layer:

- :class:`ResiliencePolicy` — the per-client knob bundle: total request
  deadline, connect timeout, inter-item stall bound, pre-first-token retry
  budget with exponential backoff + jitter, and circuit-breaker tuning.
- :class:`Deadline` — a monotonic time budget threaded from the HTTP edge
  through ``EndpointClient`` into the RPC header, so workers can shed
  requests that expired in flight.
- :class:`CircuitBreaker` — per-instance closed → open → half-open state
  machine; repeatedly-failing instances are ejected from routing until a
  half-open probe proves them healthy again.

Semantics contract (docs/resilience.md): pre-first-token failures fail over
freely. After the first token the request is *pinned* — but a pinned stream
that dies with a TRANSPORT failure (reset, stall, worker reaped/killed) is
no longer a dead end: :class:`StreamJournal` carries everything needed to
rebuild the stream on another instance (prompt + every emitted token id +
the remaining token budget), and ``EndpointClient.generate`` re-admits it
as ``prompt+generated`` with a decremented budget. Only when resume is off
(``DYN_TPU_RESUME=0``), exhausted, or impossible (non-token-level payload,
engine-semantic error) does the failure surface in-band.

Reference analogue: the reference leans on NATS redelivery + etcd liveness
(SURVEY.md §5); this is the equivalent capability re-designed for the
direct-dial data plane.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

# Canonical message prefix for deadline errors crossing process boundaries as
# Annotated error envelopes; the HTTP edge maps it to 504 vs the generic 502.
DEADLINE_ERROR = "deadline exceeded"


class DeadlineExceeded(TimeoutError):
    """The request's total time budget ran out (connect, queueing, or an
    inter-item gap). Not retryable: the budget is already spent."""


class RetryableRpcError(ConnectionError):
    """A worker rejected the request before streaming anything (draining,
    endpoint briefly unregistered) — safe to fail over to another instance."""


class WorkerStalled(ConnectionError):
    """The worker accepted the request but exceeded the inter-item stall
    bound without producing anything — treated like a dead connection."""


class NoHealthyInstances(RuntimeError):
    """No live instance is available to try (empty set, or every breaker
    open and the last-ditch pass also failed)."""


class AllInstancesFailed(ConnectionError):
    """The pre-first-token retry budget is exhausted; carries the last
    underlying failure as ``__cause__``."""


def _monotonic() -> float:
    return time.monotonic()


class Deadline:
    """A monotonic time budget. ``budget=None`` means unlimited."""

    __slots__ = ("_t0", "_budget", "_clock")

    def __init__(self, budget: Optional[float], clock: Callable[[], float] = _monotonic):
        self._clock = clock
        self._t0 = clock()
        self._budget = budget

    @classmethod
    def after(cls, budget: Optional[float],
              clock: Callable[[], float] = _monotonic) -> "Deadline":
        return cls(budget, clock)

    @property
    def budget(self) -> Optional[float]:
        return self._budget

    def remaining(self) -> Optional[float]:
        """Seconds left (may be ≤ 0); None when unlimited."""
        if self._budget is None:
            return None
        return self._budget - (self._clock() - self._t0)

    @property
    def expired(self) -> bool:
        rem = self.remaining()
        return rem is not None and rem <= 0

    def bound(self, timeout: Optional[float]) -> Optional[float]:
        """Combine with another timeout: the tighter of the two (None = no
        bound from that side)."""
        rem = self.remaining()
        if rem is None:
            return timeout
        if timeout is None:
            return max(rem, 0.0)
        return max(min(rem, timeout), 0.0)

    def check(self, what: str = "") -> None:
        if self.expired:
            raise DeadlineExceeded(
                f"{DEADLINE_ERROR}{f' ({what})' if what else ''}: "
                f"budget {self._budget:.3f}s spent"
            )


# knob parsers live in the one shared home (runtime/envknobs.py): _env_int
# is the count contract where 0 is a misconfig, _env_count the one where 0
# is a policy (DYN_TPU_RESUME=0 = resume off)
from dynamo_tpu.runtime.envknobs import (  # noqa: E402
    env_nonneg_int as _env_count,
    env_opt_pos_float as _env_float,
    env_pos_int as _env_int,
)


@dataclass
class ResiliencePolicy:
    """Per-client resilience knobs. The defaults keep today's behavior for
    patient callers (no total deadline) while bounding the failure modes
    that used to hang or error: connects time out, stalled workers are cut,
    and pre-first-token failures fail over instead of surfacing.

    ``request_timeout``      total budget for the request (None = unlimited);
                             propagated to the worker in the RPC header.
    ``connect_timeout``      per-attempt dial bound.
    ``inter_item_timeout``   max gap between stream items (None = unlimited);
                             also bounds time-to-first-token.
    ``max_attempts``         pre-first-token tries across instances.
    ``backoff_*`` / ``jitter`` exponential backoff between attempts;
                             jitter is a 0..jitter fraction added on top.
    ``breaker_*``            consecutive-failure threshold, open-state
                             cooldown, and half-open probe admission count.
    ``resume_attempts``      mid-stream recoveries per request: a pinned
                             stream cut by a *transport* failure after its
                             first token is re-admitted on another instance
                             as prompt+generated (docs/resilience.md
                             §Mid-stream resume). 0 = off — exact pinned
                             in-band-error behavior, zero journal overhead.
    ``resume_budget_s``      total wall-clock a single request may spend on
                             resume re-admissions before the failure
                             surfaces in-band.
    ``seed``                 fixes the jitter RNG (tests / reproducibility).
    """

    request_timeout: Optional[float] = None
    connect_timeout: float = 5.0
    inter_item_timeout: Optional[float] = None
    max_attempts: int = 3
    backoff_base: float = 0.05
    backoff_multiplier: float = 2.0
    backoff_max: float = 2.0
    jitter: float = 0.5
    breaker_threshold: int = 5
    breaker_cooldown: float = 5.0
    breaker_half_open_probes: int = 1
    resume_attempts: int = 1
    resume_budget_s: float = 30.0
    seed: Optional[int] = None

    def rng(self) -> random.Random:
        return random.Random(self.seed)

    def backoff(self, attempt: int, rng: Optional[random.Random] = None) -> float:
        """Delay before retry ``attempt`` (1-based): exponential with jitter."""
        base = min(
            self.backoff_base * (self.backoff_multiplier ** max(attempt - 1, 0)),
            self.backoff_max,
        )
        if self.jitter <= 0:
            return base
        r = (rng or random).random()
        return base * (1.0 + self.jitter * r)

    @classmethod
    def from_env(cls, prefix: str = "DYN_TPU_") -> "ResiliencePolicy":
        """Build a policy from ``DYN_TPU_REQUEST_TIMEOUT`` etc. Unset or
        malformed values keep the defaults. ``0`` disables the *optional*
        timeouts (``REQUEST_TIMEOUT``, ``INTER_ITEM_TIMEOUT`` → unlimited);
        the knobs that must stay positive (``CONNECT_TIMEOUT``,
        ``BREAKER_COOLDOWN``) fall back to their defaults when ≤ 0."""
        d = cls()
        return cls(
            request_timeout=_env_float(prefix + "REQUEST_TIMEOUT", d.request_timeout),
            connect_timeout=_env_float(prefix + "CONNECT_TIMEOUT", d.connect_timeout)
            or d.connect_timeout,
            inter_item_timeout=_env_float(
                prefix + "INTER_ITEM_TIMEOUT", d.inter_item_timeout
            ),
            max_attempts=max(1, _env_int(prefix + "MAX_ATTEMPTS", d.max_attempts)),
            breaker_threshold=max(
                1, _env_int(prefix + "BREAKER_THRESHOLD", d.breaker_threshold)
            ),
            breaker_cooldown=_env_float(
                prefix + "BREAKER_COOLDOWN", d.breaker_cooldown
            )
            or d.breaker_cooldown,
            resume_attempts=_env_count(prefix + "RESUME", d.resume_attempts),
            resume_budget_s=_env_float(
                prefix + "RESUME_BUDGET", d.resume_budget_s
            )
            or d.resume_budget_s,
        )


# Breaker states (plain strings so they read well in logs/metrics).
CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


@dataclass
class _BreakerSlot:
    state: str = CLOSED
    consecutive_failures: int = 0
    opened_at: float = 0.0
    half_open_inflight: int = 0


class CircuitBreaker:
    """Per-key (endpoint instance) circuit breaker.

    closed    — all traffic admitted; ``threshold`` consecutive failures
                trip the breaker open.
    open      — no traffic for ``cooldown`` seconds.
    half_open — up to ``half_open_probes`` concurrent probes admitted;
                one success closes the breaker, one failure re-opens it
                (restarting the cooldown).
    """

    def __init__(
        self,
        threshold: int = 5,
        cooldown: float = 5.0,
        half_open_probes: int = 1,
        clock: Callable[[], float] = _monotonic,
    ):
        self.threshold = max(1, threshold)
        self.cooldown = cooldown
        self.half_open_probes = max(1, half_open_probes)
        self._clock = clock
        self._slots: Dict[str, _BreakerSlot] = {}

    def _slot(self, key: str) -> _BreakerSlot:
        slot = self._slots.get(key)
        if slot is None:
            slot = self._slots[key] = _BreakerSlot()
        return slot

    def state(self, key: str) -> str:
        slot = self._slots.get(key)
        if slot is None:
            return CLOSED
        if slot.state == OPEN and self._clock() - slot.opened_at >= self.cooldown:
            return HALF_OPEN
        return slot.state

    def available(self, key: str) -> bool:
        """Pure check: may a request be routed to ``key`` right now? Safe to
        call while *filtering* candidates — it never consumes a probe slot
        (that's :meth:`acquire`, called once for the chosen instance)."""
        st = self.state(key)
        if st == CLOSED:
            return True
        if st == OPEN:
            return False
        slot = self._slots[key]
        return slot.half_open_inflight < self.half_open_probes

    def acquire(self, key: str) -> None:
        """Commit a routing decision to ``key``: in half-open state this
        consumes a probe slot (released by record_success/record_failure)."""
        slot = self._slots.get(key)
        if slot is None or slot.state == CLOSED:
            return
        if self.state(key) == HALF_OPEN:
            if slot.state == OPEN:  # cooldown just elapsed: materialize
                slot.state = HALF_OPEN
                slot.half_open_inflight = 0
            slot.half_open_inflight += 1

    def release(self, key: str) -> None:
        """Un-commit an :meth:`acquire` that resolved with *neither* success
        nor failure (deadline expiry, abandoned stream, application error):
        the half-open probe slot must return to the pool or the instance
        stays ejected forever."""
        slot = self._slots.get(key)
        if slot is not None and slot.half_open_inflight > 0:
            slot.half_open_inflight -= 1

    def record_success(self, key: str) -> None:
        slot = self._slots.get(key)
        if slot is None:
            return
        slot.state = CLOSED
        slot.consecutive_failures = 0
        slot.half_open_inflight = 0

    def record_failure(self, key: str) -> None:
        slot = self._slot(key)
        if slot.state == HALF_OPEN:
            # failed probe: straight back to open, cooldown restarts
            slot.state = OPEN
            slot.opened_at = self._clock()
            slot.half_open_inflight = 0
            return
        slot.consecutive_failures += 1
        if slot.consecutive_failures >= self.threshold and slot.state != OPEN:
            slot.state = OPEN
            slot.opened_at = self._clock()

    def forget(self, key: str) -> None:
        """Drop state for an instance that left the live set."""
        self._slots.pop(key, None)

    def prune(self, live_keys) -> None:
        """Drop state for every instance not in ``live_keys`` — leak
        containment for recovery paths that replace the live set wholesale
        without per-instance delete events."""
        for key in [k for k in self._slots if k not in live_keys]:
            del self._slots[key]

    def snapshot(self) -> Dict[str, str]:
        return {k: self.state(k) for k in self._slots}


# ---------------------------------------------------------------------------
# Mid-stream resume (docs/resilience.md §Mid-stream resume)
# ---------------------------------------------------------------------------


class StreamJournal:
    """Per-request resume journal: everything needed to rebuild a live
    token stream on another worker after its instance dies mid-decode.

    The edge already accumulates emitted token ids for detokenization; this
    formalizes that accumulation where the routing decision lives
    (``EndpointClient.generate``) and rides ``EngineContext.journal`` so
    the HTTP edge can see that a resume happened (TTFT-vs-ITL attribution).

    Only token-level payloads (a ``PreprocessedRequest`` wire dict carrying
    ``token_ids``) are journal-able; anything else — raw OpenAI dicts
    routed to preprocessing workers, unary protocol requests — keeps the
    exact pinned in-band-error behavior. A stream item without per-step
    ``token_ids`` (custom engines) marks the journal non-viable the moment
    it appears: resuming would re-emit or drop content.

    ``resume_request()`` builds the re-admission payload: the new prompt is
    ``prompt + emitted`` with the token budget decremented by what the
    caller already received, and a ``resume`` marker
    (``{"prompt_len", "rng_offset"}``) tells the serving engine where the
    original prompt ended so it rebuilds sampling state — penalty counts
    over exactly the emitted suffix — instead of treating history as
    prompt. Greedy continuations are bitwise identical to an undisturbed
    stream (asserted by tests/test_resume.py); sampled (temperature > 0)
    continuations are distributionally correct but draw fresh RNG.
    """

    __slots__ = ("prompt", "emitted", "resumes", "migrations", "started",
                 "viable", "finished", "_payload")

    def __init__(self, payload: dict, clock: Callable[[], float] = _monotonic):
        self._payload = payload
        toks = payload.get("token_ids") if isinstance(payload, dict) else None
        self.viable = (
            isinstance(toks, list)
            and all(isinstance(t, int) for t in toks)
        )
        self.prompt: List[int] = list(toks) if self.viable else []
        self.emitted: List[int] = []
        self.resumes = 0
        # live migrations followed (docs/resilience.md §Live migration):
        # planned re-homes onto a drain target's staged KV. Counted apart
        # from `resumes` — they consume no resume budget (nothing failed)
        # — but the edge attributes their gap to ITL exactly like a resume.
        self.migrations = 0
        self.finished = False
        self.started = clock()

    def note(self, data: Any) -> None:
        """Record one stream item's payload (an ``LLMEngineOutput`` wire
        dict). Called once per item on the hot path: two dict probes when
        the item is token-shaped."""
        if not self.viable or not isinstance(data, dict):
            return
        toks = data.get("token_ids")
        if isinstance(toks, list):
            self.emitted.extend(int(t) for t in toks)
        elif toks is not None or "finish_reason" not in data:
            # an item that is neither token-bearing nor a bare finish frame:
            # this stream's content is not reconstructible from token ids
            self.viable = False
        if data.get("finish_reason"):
            self.finished = True

    def resume_request(self) -> Optional[dict]:
        """The re-admission payload, or None when this stream cannot be
        resumed (non-token payload, finish already delivered, or a token
        budget that is already spent)."""
        if not self.viable or self.finished:
            return None
        p = dict(self._payload)
        p["token_ids"] = self.prompt + self.emitted
        sc = dict(p.get("stop_conditions") or {})
        n = len(self.emitted)
        max_t = sc.get("max_tokens")
        if max_t is not None:
            if n >= int(max_t):
                return None  # budget spent: the finish frame died with the worker
            sc["max_tokens"] = int(max_t) - n
        if sc.get("min_tokens") is not None:
            sc["min_tokens"] = max(int(sc["min_tokens"]) - n, 0)
        p["stop_conditions"] = sc
        # prompt_len: where sampling-state history begins on the new worker;
        # rng_offset: how many draws the original stream already consumed
        # (carried for engines with per-request RNG streams — the JAX
        # engine's step-keyed RNG documents sampled resumes as fresh-draw)
        p["resume"] = {"prompt_len": len(self.prompt), "rng_offset": n}
        return p


# process-global resume outcome counters: every EndpointClient in the
# process feeds them, attach_kv_publishing / the frontend /metrics render
# them, and the cluster aggregator sums them into dynamo_cluster_resume_*.
_RESUME_LOCK = threading.Lock()
_RESUME_TOTAL = 0
_RESUME_FAILED_TOTAL = 0


def note_resume(failed: bool = False) -> None:
    global _RESUME_TOTAL, _RESUME_FAILED_TOTAL
    with _RESUME_LOCK:
        if failed:
            _RESUME_FAILED_TOTAL += 1
        else:
            _RESUME_TOTAL += 1


def resume_counters() -> tuple:
    """(resume_total, resume_failed_total) — cumulative for this process."""
    with _RESUME_LOCK:
        return _RESUME_TOTAL, _RESUME_FAILED_TOTAL


def reset_resume_counters() -> None:
    """Test/bench hook: zero the process-global resume counters."""
    global _RESUME_TOTAL, _RESUME_FAILED_TOTAL
    with _RESUME_LOCK:
        _RESUME_TOTAL = 0
        _RESUME_FAILED_TOTAL = 0
