"""Direct-dial streaming RPC between clients and workers.

Design delta vs the reference (intentional): the reference pushes requests
through NATS and opens a TCP connect-back for responses (two hops + a broker;
egress/push.rs:37-180, tcp/server.rs). Here discovery (statestore) hands the
client the worker's address and the client dials it directly — request and
response stream ride ONE multiplexed TCP connection with the same framed
codec. Same capability (streaming, cancellation, graceful drain), one less
network hop on every token.

Wire protocol (header JSON + body):
  client→worker: {id, op:"generate", endpoint, deadline_ms?, traceparent?}
                 body=request JSON
                 {id, op:"stop"|"kill"}        (mid-stream cancellation)
                 {id, op:"ping"}               (liveness probe, ``__ping__``)
                 {id, op:"trace_dump", limit?, trace_id?}  (flight recorder)
                 {id, op:"telemetry_dump"}     (SLO/perf state, llmctl slo)
                 {id, op:"profile_dump", since_s?}  (dispatch timeline,
                                                llmctl profile capture)
  worker→client: {id, op:"item"}  body=one Annotated dict JSON
                 {id, op:"done"}
                 {id, op:"error", message, code?, retryable?}
                 {id, op:"pong", health, load} (probe reply)
                 {id, op:"trace_data", count}  body=JSON list of traces
                 {id, op:"telemetry_data"}     body=JSON telemetry state
                 {id, op:"profile_data", count}  body=JSON profiling state

``traceparent`` (W3C wire form, runtime/tracing.py) threads the caller's
trace context through so the worker's serve/engine spans join the same
trace; absent or malformed values start a fresh root trace (old binaries
interoperate). ``trace_dump`` reads the worker's in-process flight
recorder — ``llmctl trace dump/show`` ride it.

``ping`` answers through the SAME dispatch gate ordinary requests pass
(faults.serve_gate) and carries the worker's health-plane state — a zombie
worker (socket alive, engine wedged) times the probe out instead of
answering from a healthy accept loop, and a self-diagnosed ``unhealthy``
worker says so. EndpointClient probes silent instances with it.

``deadline_ms`` is the request's *remaining* budget at send time (relative,
not wall-clock — hosts don't share clocks); the worker sheds requests whose
budget is already spent and stops streams whose budget expires mid-flight.
Error replies carry ``retryable`` (safe to fail over to another instance:
draining, overloaded, transport trouble) and ``code`` ("deadline" |
"draining" | "overloaded" | "unknown_endpoint") so clients can map them
without string matching. ``overloaded`` replies additionally carry
``queue_depth`` + ``retry_after_ms``, and terminal replies (``done`` /
``error``) piggyback a compact ``load`` snapshot so routers keep a live
per-instance load view at zero extra round trips.

Backpressure: every response stream writes through a bounded per-stream
send queue (``AdmissionPolicy.send_queue_cap``). A slow reader fills the
queue and the generator *pauses* instead of buffering tokens in worker
memory; a reader that stays stalled past ``slow_consumer_timeout`` gets the
stream cut (engine context killed).
"""

from __future__ import annotations

import asyncio
import contextlib
import itertools
import json
import logging
import time
from typing import Any, AsyncIterator, Callable, Dict, Optional, Tuple

from dynamo_tpu.runtime import faults, tracing
from dynamo_tpu.runtime.admission import (
    AdmissionController,
    LoadSnapshot,
    OverloadedError,
    SlowConsumer,
)
from dynamo_tpu.runtime.annotated import Annotated
from dynamo_tpu.runtime.codec import CodecError, TwoPartMessage, read_frame, write_frame
from dynamo_tpu.runtime.engine import AsyncEngine, Context
from dynamo_tpu.runtime.resilience import (
    DEADLINE_ERROR,
    Deadline,
    DeadlineExceeded,
    RetryableRpcError,
    WorkerStalled,
)

logger = logging.getLogger(__name__)


class _StreamSender:
    """Bounded per-stream send queue + drain task.

    The generator side awaits :meth:`send`, which blocks once ``cap`` frames
    are queued — that pause IS the backpressure (the engine stream stops
    being pulled). A queue that stays full past ``stall_timeout`` means the
    reader is gone or wedged: :meth:`send` raises :class:`SlowConsumer` so
    the caller can kill the stream instead of holding tokens forever.
    """

    def __init__(self, writer: asyncio.StreamWriter, write_lock: asyncio.Lock,
                 cap: int, stall_timeout: float):
        self.cap = max(cap, 1)
        self.stall_timeout = stall_timeout
        self.peak = 0  # high-water mark, for tests/metrics
        self.dead: Optional[BaseException] = None
        self._q: asyncio.Queue = asyncio.Queue(maxsize=self.cap)
        self._task = asyncio.create_task(self._drain(writer, write_lock))

    async def _drain(self, writer: asyncio.StreamWriter, lock: asyncio.Lock) -> None:
        while True:
            frame = await self._q.get()
            if frame is None:
                return
            try:
                async with lock:
                    await write_frame(writer, frame)
            except (ConnectionError, OSError) as e:
                self.dead = e
                return

    async def send(self, header: dict, payload: bytes = b"") -> None:
        if self.dead is not None:
            raise ConnectionError(f"stream writer dead: {self.dead}")
        frame = TwoPartMessage(json.dumps(header).encode(), payload)
        try:
            self._q.put_nowait(frame)
        except asyncio.QueueFull:
            # queue full: the reader is behind. Block (backpressure) up to
            # the slow-consumer bound, then cut the stream.
            try:
                await asyncio.wait_for(self._q.put(frame), self.stall_timeout)
            except asyncio.TimeoutError:
                raise SlowConsumer(
                    f"send queue full ({self.cap}) for "
                    f"{self.stall_timeout:.1f}s — reader stalled"
                ) from None
        self.peak = max(self.peak, self._q.qsize())
        if self.dead is not None:
            raise ConnectionError(f"stream writer dead: {self.dead}")

    async def close(self) -> None:
        """Flush queued frames and stop the drain task. Must be awaited from
        the request task's ``finally`` — if that task is itself being
        cancelled, the drain task is cancelled too (never leaked). BOTH
        waits are bounded: a reader whose TCP buffer wedged mid-``drain()``
        would otherwise pin this request in ``_inflight`` forever, eating
        an admission slot on a healthy worker."""
        if self.dead is None and not self._task.done():
            try:
                await asyncio.wait_for(self._q.put(None), self.stall_timeout)
                # wait_for cancels the drain task on timeout — exactly the
                # slow-consumer cut, applied at stream end
                await asyncio.wait_for(self._task, self.stall_timeout)
                return
            except asyncio.TimeoutError:
                pass  # reader wedged mid-close: abandon the flush
            except asyncio.CancelledError:
                self._task.cancel()
                raise
        self._task.cancel()


class RequestTrack:
    """One in-flight request's registry entry — the health plane's view.

    Filled in as ``_serve_request`` progresses (deadline, engine context,
    stream sender); the stuck-request reaper sweeps these to find requests
    whose deadline expired without the stream ever terminating."""

    __slots__ = ("req_id", "started", "deadline", "ctx", "sender", "task",
                 "reaped", "span")

    def __init__(self, req_id):
        self.req_id = req_id
        self.started = time.monotonic()
        self.deadline: Optional[Deadline] = None
        self.ctx: Optional[Context] = None
        self.sender = None
        self.task: Optional[asyncio.Task] = None
        self.reaped = False
        self.span = None  # tracing.Span while serving (reaper adds events)


def _record_shed_span(h: dict, code: str, **attrs) -> None:
    """Even a rejected request leaves a trace: operators debugging "my
    request vanished" find the shed marker joined to the caller's trace."""
    tracing.record_event_span(
        "rpc.shed",
        parent=tracing.parse_traceparent(h.get("traceparent")),
        status="overloaded",
        attributes={"code": code, "request_id": h.get("request_id"), **attrs},
    )


class RpcServer:
    """Serves registered engines over TCP; tracks in-flight requests and
    drains them on stop (reference PushEndpoint semantics)."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0,
                 admission: Optional[AdmissionController] = None):
        self.host = host
        self.port = port
        self._engines: Dict[str, AsyncEngine] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._inflight: set = set()
        self._tracks: set = set()  # RequestTrack per in-flight request
        self._draining = False
        self.admission = admission or AdmissionController()
        self.send_queue_peak = 0  # high-water mark across all streams
        # health plane (runtime/health.py): the monitor attaches itself here;
        # its state rides every load snapshot and every pong
        self.health = None
        self.reaped_total = 0
        # request outcome counters (telemetry plane): cumulative, two int
        # increments per REQUEST — never per token. The cluster SLO engine
        # diffs them for the error-rate objective; `cancelled` is excluded
        # from errors (client hangups are not service failures).
        self.requests_total = 0
        self.requests_errored = 0

    def engines(self) -> list:
        """Registered engines (the health monitor sweeps these for
        heartbeats and sub-engine health self-reports)."""
        return list(self._engines.values())

    def health_state(self) -> str:
        return self.health.state if self.health is not None else "healthy"

    def register(self, endpoint: str, engine: AsyncEngine) -> None:
        self._engines[endpoint] = engine
        # engines exposing capacity (engine_jax metrics_snapshot) feed the
        # admission gate + load snapshots; wrapper engines without it leave
        # the gate bounding the RPC pending count alone
        if self.admission.engine_probe is None and hasattr(engine, "metrics_snapshot"):
            self.admission.engine_probe = engine.metrics_snapshot

    @property
    def draining(self) -> bool:
        return self._draining

    def set_draining(self, flag: bool) -> None:
        self._draining = bool(flag)

    def load_snapshot(self) -> LoadSnapshot:
        snap = self.admission.snapshot(len(self._inflight), draining=self._draining)
        snap.health = self.health_state()
        return snap

    async def start(self) -> None:
        from dynamo_tpu.runtime.netutil import TrackedServer

        self._server = TrackedServer(self._handle, self.host, self.port)
        self.port = await self._server.start()
        logger.info("rpc server listening on %s:%d", self.host, self.port)

    async def stop(self, drain_timeout: float = 10.0) -> None:
        self._draining = True
        if self._server:
            self._server.close_listener()
        if self._inflight:
            done, pending = await asyncio.wait(self._inflight, timeout=drain_timeout)
            for t in pending:
                t.cancel()
        if self._server:
            await self._server.stop()

    @property
    def inflight_count(self) -> int:
        return len(self._inflight)

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        contexts: Dict[int, Context] = {}
        write_lock = asyncio.Lock()
        conn_tasks: set = set()
        try:
            while True:
                try:
                    frame = await read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    return
                except CodecError as e:
                    # garbage bytes / corrupt frame: this connection's stream
                    # position is unrecoverable — drop it, leave every other
                    # connection (and this server) untouched
                    logger.warning("malformed rpc frame, closing connection: %s", e)
                    return
                try:
                    h = json.loads(frame.header)
                    if not isinstance(h, dict):
                        raise ValueError("header is not a JSON object")
                except (ValueError, UnicodeDecodeError) as e:
                    logger.warning("malformed rpc header, closing connection: %s", e)
                    return
                op = h.get("op")
                if op == "generate":
                    if h.get("id") is None:
                        async with write_lock:
                            await write_frame(writer, TwoPartMessage(
                                json.dumps({"id": None, "op": "error",
                                            "message": "missing request id"}).encode(),
                                b""))
                        continue
                    if self._draining:
                        # shed replies never reach _serve_request: count
                        # them here or the overload-share SLO divides by a
                        # total that excludes exactly the shed traffic
                        self.requests_total += 1
                        _record_shed_span(h, "draining")
                        async with write_lock:
                            await write_frame(writer, TwoPartMessage(
                                json.dumps({"id": h["id"], "op": "error",
                                            "message": "worker draining",
                                            "code": "draining",
                                            "retryable": True,
                                            "load": self.load_snapshot().to_wire(),
                                            }).encode(), b""))
                        continue
                    shed = self.admission.try_admit(
                        len(self._inflight), tenant=h.get("tenant")
                    )
                    if shed is not None:
                        self.requests_total += 1  # see draining note above
                        # bounded degradation: answer NOW with a typed,
                        # retryable rejection + back-off hint instead of
                        # queueing the request toward a timeout. The gate's
                        # own snapshot rides the reply — no second engine
                        # probe at the worker's busiest moment.
                        _record_shed_span(
                            h, "overloaded", queue_depth=shed.queue_depth,
                            **({"tenant": shed.tenant} if shed.tenant else {}),
                        )
                        load = shed.load or self.load_snapshot()
                        load.draining = self._draining
                        reply = {"id": h["id"], "op": "error",
                                 "message": str(shed),
                                 "code": "overloaded",
                                 "retryable": True,
                                 "queue_depth": shed.queue_depth,
                                 "retry_after_ms": shed.retry_after_ms,
                                 "load": load.to_wire()}
                        if shed.tenant:
                            # per-tenant rate shed: the retry hint is THIS
                            # tenant's bucket refill — failover to a
                            # sibling would just drain its bucket there
                            reply["tenant"] = shed.tenant
                        async with write_lock:
                            await write_frame(writer, TwoPartMessage(
                                json.dumps(reply).encode(), b""))
                        continue
                    track = RequestTrack(h["id"])
                    task = asyncio.create_task(
                        self._serve_request(h, frame.body, writer, write_lock,
                                            contexts, track)
                    )
                    track.task = task
                    self._inflight.add(task)
                    self._tracks.add(track)
                    conn_tasks.add(task)
                    task.add_done_callback(self._inflight.discard)
                    task.add_done_callback(conn_tasks.discard)
                    task.add_done_callback(
                        lambda _t, tr=track: self._tracks.discard(tr)
                    )
                elif op == "ping":
                    # liveness probe: answered by a task so a wedged serve
                    # gate hangs the PONG (the probe's whole point), never
                    # this connection's read loop
                    t = asyncio.create_task(
                        self._pong(h.get("id"), writer, write_lock)
                    )
                    conn_tasks.add(t)
                    t.add_done_callback(conn_tasks.discard)
                elif op == "trace_dump":
                    t = asyncio.create_task(
                        self._trace_dump(h, writer, write_lock)
                    )
                    conn_tasks.add(t)
                    t.add_done_callback(conn_tasks.discard)
                elif op == "telemetry_dump":
                    t = asyncio.create_task(
                        self._telemetry_dump(h, writer, write_lock)
                    )
                    conn_tasks.add(t)
                    t.add_done_callback(conn_tasks.discard)
                elif op == "profile_dump":
                    t = asyncio.create_task(
                        self._profile_dump(h, writer, write_lock)
                    )
                    conn_tasks.add(t)
                    t.add_done_callback(conn_tasks.discard)
                elif op in ("stop", "kill"):
                    ctx = contexts.get(h.get("id"))
                    if ctx is not None:
                        if op == "kill":
                            ctx.context.kill()
                        else:
                            ctx.context.stop_generating()
        finally:
            # client went away: kill everything it had in flight on this conn
            for ctx in contexts.values():
                ctx.context.kill()
            for t in list(conn_tasks):
                t.cancel()
            writer.close()

    async def _pong(self, req_id, writer, write_lock) -> None:
        """Answer a ``ping`` THROUGH the serve gate (the path requests take),
        carrying health state + load. A wedged worker never answers; the
        prober's timeout is the detection."""
        try:
            await faults.serve_gate("rpc", f"{self.host}:{self.port}")
            header = {
                "id": req_id, "op": "pong",
                "health": self.health_state(),
                "load": self.load_snapshot().to_wire(),
            }
            async with write_lock:
                await write_frame(
                    writer, TwoPartMessage(json.dumps(header).encode(), b"")
                )
        except (ConnectionError, OSError):
            pass  # prober gone; nothing to answer

    async def _trace_dump(self, h, writer, write_lock) -> None:
        """Answer a ``trace_dump`` with this process's flight-recorder
        contents (bounded by the recorder's own ring — never unbounded).
        Pure local-memory read: no engine involvement, safe while wedged."""
        try:
            traces = tracing.recorder().traces(
                limit=int(h.get("limit") or 0),
                trace_id=h.get("trace_id"),
            )
            body = json.dumps(traces).encode()
            header = {"id": h.get("id"), "op": "trace_data",
                      "count": len(traces)}
            async with write_lock:
                await write_frame(
                    writer, TwoPartMessage(json.dumps(header).encode(), body)
                )
        except (ConnectionError, OSError):
            pass  # requester gone
        except Exception:
            logger.exception("trace_dump failed")

    async def _telemetry_dump(self, h, writer, write_lock) -> None:
        """Answer a ``telemetry_dump`` with this process's telemetry state
        (uptime, build identity, SLO report, and — in an aggregator
        process — the cluster rollup). Pure local-memory read like
        ``trace_dump``: safe while the engine is wedged, which is exactly
        when an operator runs ``llmctl slo status``."""
        try:
            from dynamo_tpu.runtime import telemetry

            body = json.dumps(telemetry.dump_state()).encode()
            header = {"id": h.get("id"), "op": "telemetry_data"}
            async with write_lock:
                await write_frame(
                    writer, TwoPartMessage(json.dumps(header).encode(), body)
                )
        except (ConnectionError, OSError):
            pass  # requester gone
        except Exception:
            logger.exception("telemetry_dump failed")

    async def _profile_dump(self, h, writer, write_lock) -> None:
        """Answer a ``profile_dump`` with this process's performance-
        attribution state (runtime/profiling.py: dispatch timeline records,
        jit-compile events, summary, frontend CPU/lag when present).
        Pure local-memory read like ``trace_dump`` — safe while the engine
        is wedged, which is exactly when an operator runs ``llmctl profile
        capture``. ``since_s`` bounds the window; a process that never
        armed DYN_TPU_PROFILE answers ``enabled: false`` with empty
        sections (never an error — the CLI tells the operator which
        workers have the knob off)."""
        try:
            from dynamo_tpu.runtime import profiling

            since = h.get("since_s")
            state = profiling.dump_state(
                float(since) if since is not None else None
            )
            body = json.dumps(state).encode()
            header = {"id": h.get("id"), "op": "profile_data",
                      "count": len(state.get("records", []))}
            async with write_lock:
                await write_frame(
                    writer, TwoPartMessage(json.dumps(header).encode(), body)
                )
        except (ConnectionError, OSError):
            pass  # requester gone
        except Exception:
            logger.exception("profile_dump failed")

    async def reap_expired(self, grace: float) -> int:
        """Abort in-flight requests whose deadline expired more than
        ``grace`` seconds ago: emit a terminal error item, kill the engine
        context (the engine then returns the request's slot and KV blocks),
        and cancel the serve task. This is leak recovery — the in-stream
        deadline check only runs when an item arrives, so a request whose
        engine never yields would otherwise hold its RPC slot, engine slot,
        and KV blocks forever. Driven by the health monitor's check loop."""
        reaped = 0
        for track in list(self._tracks):
            if track.reaped or track.deadline is None:
                continue
            rem = track.deadline.remaining()
            if rem is None or rem > -grace:
                continue
            track.reaped = True
            reaped += 1
            self.reaped_total += 1
            if track.span is not None:
                track.span.add_event("reaped", overdue_s=round(-rem, 3))
            logger.warning(
                "reaping stuck request %s (deadline exceeded by %.1fs, "
                "age %.1fs)", track.req_id, -rem,
                time.monotonic() - track.started,
            )
            if track.sender is not None:
                # terminal error item first — the cancel below flushes the
                # sender queue, so the client observes the termination
                try:
                    await asyncio.wait_for(track.sender.send({
                        "id": track.req_id, "op": "error",
                        "message": (
                            f"{DEADLINE_ERROR}: request reaped "
                            f"{-rem:.1f}s past its deadline (stuck)"
                        ),
                        "code": "deadline",
                        "load": self.load_snapshot().to_wire(),
                    }), 1.0)
                except (asyncio.TimeoutError, ConnectionError, OSError):
                    pass  # reader gone/stalled: the kill below still runs
            if track.ctx is not None:
                track.ctx.context.kill()
            if track.task is not None and not track.task.done():
                track.task.cancel()
        return reaped

    async def _serve_request(self, h, body, writer, write_lock, contexts,
                             track: Optional[RequestTrack] = None) -> None:
        req_id = h["id"]
        track = track or RequestTrack(req_id)
        engine = self._engines.get(h.get("endpoint", ""))
        policy = self.admission.policy
        # all frames for this stream ride a BOUNDED queue: a slow reader
        # pauses the generator (backpressure) instead of growing worker
        # memory, and a stalled one gets the stream cut below
        sender = _StreamSender(writer, write_lock, policy.send_queue_cap,
                               policy.slow_consumer_timeout)
        track.sender = sender

        async def send(header: dict, payload: bytes = b"") -> None:
            await sender.send(header, payload)

        def load_wire() -> dict:
            return self.load_snapshot().to_wire()

        # serve span: joins the caller's trace via the header's traceparent
        # (absent/malformed → fresh root). Per-PHASE, never per token: the
        # item loop below touches it with one None-check + one int per item.
        span = tracing.start_span(
            "rpc.serve",
            parent=tracing.parse_traceparent(h.get("traceparent")),
            attributes={"endpoint": h.get("endpoint"),
                        "request_id": h.get("request_id")},
        )
        track.span = span
        outcome = "error"
        n_items = 0
        first_item_seen = False
        ctx: Optional[Context] = None
        try:
            if engine is None:
                await send({"id": req_id, "op": "error",
                            "message": f"no such endpoint {h.get('endpoint')!r}",
                            "code": "unknown_endpoint", "load": load_wire()})
                return
            # the client sends its REMAINING budget; re-anchor it to this
            # host's clock. A request that expired in the queue/network is
            # shed before it touches the engine (reference: no analogue —
            # NATS just redelivers)
            deadline: Optional[Deadline] = None
            deadline_ms = h.get("deadline_ms")
            if deadline_ms is not None:
                try:
                    deadline = Deadline.after(float(deadline_ms) / 1000.0)
                except (TypeError, ValueError):
                    deadline = None
            track.deadline = deadline
            if deadline is not None and deadline.expired:
                outcome = "deadline"
                await send({"id": req_id, "op": "error",
                            "message": f"{DEADLINE_ERROR}: expired before start",
                            "code": "deadline", "load": load_wire()})
                return
            # fault-injection dispatch gate: a `wedge` rule parks the
            # request here forever — the deterministic zombie-worker fault
            # the health plane (probes + reaper) must absorb. The dispatch
            # span makes injected wedges/delays VISIBLE in the trace: a
            # request that sat here shows the wait right where it happened.
            if span is not None:
                gate_t0 = time.perf_counter()
            await faults.serve_gate("rpc", f"{self.host}:{self.port}")
            if span is not None:
                gate_s = time.perf_counter() - gate_t0
                if gate_s > 0.001:  # only a measurable wait earns a span
                    tracing.record_span(
                        "rpc.dispatch_gate", gate_t0, gate_t0 + gate_s,
                        parent=span,
                    )
            try:
                payload = json.loads(body) if body else None
                ctx = Context(payload, request_id=h.get("request_id"))
                # the engine parents its queue/prefill/decode spans here
                ctx.context.trace = span
                tenant = h.get("tenant")
                if tenant:
                    # QoS identity rides the context into the engine's
                    # fair scheduler / KV budgets (runtime/qos.py)
                    ctx.context.tenant = str(tenant)
                    if span is not None:
                        span.set_attribute("tenant", str(tenant))
                contexts[req_id] = ctx
                track.ctx = ctx
                stream = engine.generate(ctx)
                if hasattr(stream, "__await__"):
                    stream = await stream
                sent = 0
                async for item in stream:
                    if deadline is not None and deadline.expired:
                        # nobody is waiting for these tokens anymore: stop
                        # the engine and tell the client why the stream ended
                        outcome = "deadline"
                        ctx.context.kill()
                        await send({"id": req_id, "op": "error",
                                    "message": f"{DEADLINE_ERROR}: mid-stream",
                                    "code": "deadline", "load": load_wire()})
                        return
                    if span is not None:
                        n_items += 1
                        if not first_item_seen:
                            first_item_seen = True
                            span.add_event("first_item")
                    if faults.current() is not None:
                        # per-item fault gate: a `cut` rule here is THE
                        # deterministic mid-decode worker kill (after N
                        # items, abort the connection). No injector ⇒ one
                        # call + None check per item.
                        await faults.item_gate(
                            "rpc", f"{self.host}:{self.port}", sent
                        )
                    sent += 1
                    d = item.to_dict() if isinstance(item, Annotated) else item
                    await send({"id": req_id, "op": "item"}, json.dumps(d).encode())
                outcome = "ok"
                await send({"id": req_id, "op": "done", "load": load_wire()})
            except faults.StreamCut as e:
                # injected mid-decode death: kill this request's engine
                # context and abort the WHOLE connection — from the client
                # this is indistinguishable from the worker process dying
                # (every stream on the conn sees a reset), which is exactly
                # what the chaos/resume tests need to be deterministic about
                outcome = "cut"
                logger.warning("injected stream cut for %s: %s", req_id, e)
                sender.dead = e
                if ctx is not None:
                    ctx.context.kill()
                transport = getattr(writer, "transport", None)
                if transport is not None:
                    transport.abort()
                else:
                    writer.close()
            except SlowConsumer as e:
                # reader stalled with a full queue: kill the engine context
                # and drop the stream — no reply can reach a reader that
                # stopped reading, and holding its tokens would defeat the
                # memory bound. Mark the sender dead so close() below
                # cancels instead of waiting out another flush window.
                self.admission.slow_consumer_cuts += 1
                outcome = "slow_consumer"
                logger.warning("cutting stream %s: %s", req_id, e)
                sender.dead = e
                if ctx is not None:
                    ctx.context.kill()
            except asyncio.CancelledError:
                outcome = "cancelled"
                raise
            except ConnectionError:
                raise
            except Exception as e:
                logger.exception("rpc handler error (req %s)", req_id)
                try:
                    await send({"id": req_id, "op": "error", "message": str(e),
                                "load": load_wire()})
                except (ConnectionError, SlowConsumer):
                    pass
        finally:
            if span is not None:
                # reaper cancellation lands here too: its status wins over
                # whatever the serve path had reached
                span.set_attribute("items", n_items)
                span.end("reaped" if track.reaped else outcome)
            self.requests_total += 1
            if (track.reaped or outcome not in ("ok", "cancelled")):
                self.requests_errored += 1
            contexts.pop(req_id, None)
            self.send_queue_peak = max(self.send_queue_peak, sender.peak)
            await sender.close()


def _force_push(q: asyncio.Queue, item) -> None:
    """Deliver a terminal event even to a full (slow-consumer) queue by
    dropping the oldest buffered frame — the stream is ending in an error
    either way, and the consumer must observe the termination."""
    try:
        q.put_nowait(item)
    except asyncio.QueueFull:
        with contextlib.suppress(asyncio.QueueEmpty):
            q.get_nowait()
        with contextlib.suppress(asyncio.QueueFull):
            q.put_nowait(item)


class RpcClient:
    """Multiplexed client connection to one worker."""

    # per-stream receive buffer bound: past this many undelivered frames the
    # consumer is considered slow; the read loop first blocks (propagating
    # TCP backpressure to the worker), then cuts the stream
    STREAM_QUEUE_CAP = 256
    SLOW_CONSUMER_TIMEOUT = 30.0

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._ids = itertools.count(1)
        self._streams: Dict[int, asyncio.Queue] = {}
        self._reader_task: Optional[asyncio.Task] = None
        self._send_lock = asyncio.Lock()
        self._kill_tasks: set = set()
        # per-stream cumulative stall clock: started when a stream's queue
        # first overflows, cleared only when a put succeeds WITHOUT waiting
        # — a consumer trickling one frame per grace window must not reset
        # the timer and stall the shared reader forever
        self._stall_since: Dict[Any, float] = {}
        self.closed = False
        # optional hook: piggybacked worker load snapshots from reply
        # headers (EndpointClient feeds its per-instance load view with it)
        self.on_load: Optional[Callable[[dict], None]] = None

    @classmethod
    async def connect(cls, address: str, timeout: Optional[float] = None) -> "RpcClient":
        host, _, port = address.rpartition(":")
        c = cls(host or "127.0.0.1", int(port))
        dial = faults.open_connection(c.host, c.port, plane="rpc")
        if timeout is not None:
            # asyncio.wait_for, not asyncio.timeout (py3.10 floor)
            try:
                c._reader, c._writer = await asyncio.wait_for(dial, timeout)
            except asyncio.TimeoutError:
                raise WorkerStalled(
                    f"connect to {address} timed out after {timeout:.1f}s"
                ) from None
        else:
            c._reader, c._writer = await dial
        c._reader_task = asyncio.create_task(c._read_loop())
        return c

    async def close(self) -> None:
        self.closed = True
        if self._reader_task:
            self._reader_task.cancel()
        if self._writer:
            self._writer.close()
        for q in self._streams.values():
            _force_push(q, ("error", {"message": "connection closed", "retryable": True}))

    def _cut_slow_stream(self, req_id, q: asyncio.Queue) -> None:
        """Local consumer stopped draining: drop the stream (bounded client
        memory, mirror of the server-side slow-consumer cut) and tell the
        worker to stop generating for it."""
        self._streams.pop(req_id, None)
        _force_push(q, ("error", {"message": "slow consumer: stream dropped "
                                             "locally", "retryable": False}))

        async def _kill():
            with contextlib.suppress(ConnectionError, OSError):
                await self._send({"id": req_id, "op": "kill"})

        t = asyncio.get_running_loop().create_task(_kill())
        self._kill_tasks.add(t)
        t.add_done_callback(self._kill_tasks.discard)

    async def _read_loop(self) -> None:
        try:
            while True:
                frame = await read_frame(self._reader)
                h = json.loads(frame.header)
                if not isinstance(h, dict):
                    # same hardening as the server side: a JSON-valid but
                    # non-object header must not kill the reader silently
                    raise ValueError("response header is not a JSON object")
                load = h.get("load")
                if isinstance(load, dict) and self.on_load is not None:
                    try:
                        self.on_load(load)
                    except Exception:
                        logger.debug("on_load hook failed", exc_info=True)
                q = self._streams.get(h.get("id"))
                if q is None:
                    continue
                op = h.get("op")
                if op == "item":
                    item = ("item", frame.body)
                elif op == "done":
                    item = ("done", None)
                elif op == "pong":
                    item = ("pong", {"health": h.get("health", "healthy"),
                                     "load": load})
                elif op == "trace_data":
                    item = ("trace_data", frame.body)
                elif op == "telemetry_data":
                    item = ("telemetry_data", frame.body)
                elif op == "profile_data":
                    item = ("profile_data", frame.body)
                elif op == "error":
                    item = ("error", {
                        "message": h.get("message", "remote error"),
                        "code": h.get("code"),
                        "retryable": bool(h.get("retryable")),
                        "queue_depth": h.get("queue_depth"),
                        "retry_after_ms": h.get("retry_after_ms"),
                        "tenant": h.get("tenant"),
                    })
                else:
                    continue
                try:
                    q.put_nowait(item)
                    self._stall_since.pop(h.get("id"), None)
                except asyncio.QueueFull:
                    # consumer is STREAM_QUEUE_CAP frames behind: stop
                    # reading the socket (TCP backpressure reaches the
                    # worker's bounded send queue). Blocking here stalls
                    # every stream on this multiplexed connection, so the
                    # stall budget is CUMULATIVE per stream — once a
                    # stream has spent SLOW_CONSUMER_TIMEOUT blocking the
                    # reader it is cut, even if it trickled frames through
                    rid = h.get("id")
                    now = time.monotonic()
                    start = self._stall_since.setdefault(rid, now)
                    budget = self.SLOW_CONSUMER_TIMEOUT - (now - start)
                    delivered = False
                    if budget > 0:
                        try:
                            await asyncio.wait_for(q.put(item), budget)
                            delivered = True
                        except asyncio.TimeoutError:
                            pass
                    if not delivered:
                        self._stall_since.pop(rid, None)
                        self._cut_slow_stream(rid, q)
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError):
            self.closed = True
            for q in self._streams.values():
                _force_push(q, ("error", {"message": "connection lost",
                                          "retryable": True}))
        except (CodecError, ValueError):
            # a server speaking garbage is as dead as a closed socket
            logger.warning("malformed frame from worker %s:%d", self.host, self.port)
            self.closed = True
            if self._writer:
                self._writer.close()
            for q in self._streams.values():
                _force_push(q, ("error", {"message": "malformed response frame",
                                          "retryable": True}))

    async def _send(self, header: dict, body: bytes = b"") -> None:
        async with self._send_lock:
            await write_frame(self._writer, TwoPartMessage(json.dumps(header).encode(), body))

    async def ping(self, timeout: float = 2.0) -> dict:
        """Probe the worker's liveness through the real dispatch path.

        Returns the pong payload (``{"health": ..., "load": ...}``). Raises
        :class:`WorkerStalled` when no pong arrives within ``timeout`` (a
        healthy socket whose serve path is wedged — the zombie signature)
        and ``ConnectionError`` when the transport itself is dead."""
        req_id = next(self._ids)
        q: asyncio.Queue = asyncio.Queue(maxsize=1)
        self._streams[req_id] = q
        try:
            await self._send({"id": req_id, "op": "ping"})
            try:
                kind, data = await asyncio.wait_for(q.get(), timeout)
            except asyncio.TimeoutError:
                raise WorkerStalled(
                    f"no pong from {self.host}:{self.port} within "
                    f"{timeout:.1f}s"
                ) from None
            if kind != "pong":
                info = data if isinstance(data, dict) else {}
                raise ConnectionError(
                    f"ping failed: {info.get('message', kind)}"
                )
            return data
        finally:
            self._streams.pop(req_id, None)

    async def trace_dump(
        self,
        limit: int = 0,
        trace_id: Optional[str] = None,
        timeout: float = 5.0,
    ) -> list:
        """Fetch the worker's flight-recorder traces (``llmctl trace``)."""
        req_id = next(self._ids)
        q: asyncio.Queue = asyncio.Queue(maxsize=1)
        self._streams[req_id] = q
        try:
            header: Dict[str, Any] = {"id": req_id, "op": "trace_dump"}
            if limit:
                header["limit"] = int(limit)
            if trace_id:
                header["trace_id"] = trace_id
            await self._send(header)
            try:
                kind, data = await asyncio.wait_for(q.get(), timeout)
            except asyncio.TimeoutError:
                raise WorkerStalled(
                    f"no trace_data from {self.host}:{self.port} within "
                    f"{timeout:.1f}s"
                ) from None
            if kind != "trace_data":
                info = data if isinstance(data, dict) else {}
                raise ConnectionError(
                    f"trace_dump failed: {info.get('message', kind)}"
                )
            return json.loads(data) if data else []
        finally:
            self._streams.pop(req_id, None)

    async def telemetry_dump(self, timeout: float = 5.0) -> dict:
        """Fetch the worker's telemetry state (``llmctl slo status`` /
        ``llmctl cluster status``)."""
        req_id = next(self._ids)
        q: asyncio.Queue = asyncio.Queue(maxsize=1)
        self._streams[req_id] = q
        try:
            await self._send({"id": req_id, "op": "telemetry_dump"})
            try:
                kind, data = await asyncio.wait_for(q.get(), timeout)
            except asyncio.TimeoutError:
                raise WorkerStalled(
                    f"no telemetry_data from {self.host}:{self.port} within "
                    f"{timeout:.1f}s"
                ) from None
            if kind != "telemetry_data":
                info = data if isinstance(data, dict) else {}
                raise ConnectionError(
                    f"telemetry_dump failed: {info.get('message', kind)}"
                )
            return json.loads(data) if data else {}
        finally:
            self._streams.pop(req_id, None)

    async def profile_dump(
        self, since_s: Optional[float] = None, timeout: float = 5.0
    ) -> dict:
        """Fetch the worker's performance-attribution state
        (``llmctl profile capture``)."""
        req_id = next(self._ids)
        q: asyncio.Queue = asyncio.Queue(maxsize=1)
        self._streams[req_id] = q
        try:
            header: Dict[str, Any] = {"id": req_id, "op": "profile_dump"}
            if since_s is not None:
                header["since_s"] = float(since_s)
            await self._send(header)
            try:
                kind, data = await asyncio.wait_for(q.get(), timeout)
            except asyncio.TimeoutError:
                raise WorkerStalled(
                    f"no profile_data from {self.host}:{self.port} within "
                    f"{timeout:.1f}s"
                ) from None
            if kind != "profile_data":
                info = data if isinstance(data, dict) else {}
                raise ConnectionError(
                    f"profile_dump failed: {info.get('message', kind)}"
                )
            return json.loads(data) if data else {}
        finally:
            self._streams.pop(req_id, None)

    async def generate(
        self,
        endpoint: str,
        request: Any,
        context: Optional[Context] = None,
        deadline: Optional[Deadline] = None,
        inter_item_timeout: Optional[float] = None,
        raise_transport: bool = False,
    ) -> AsyncIterator[Annotated]:
        """Call a remote endpoint; yields Annotated items. Propagates local
        context stop/kill to the worker.

        ``deadline`` bounds the whole stream (and rides the RPC header so
        the worker sheds expired requests); ``inter_item_timeout`` bounds
        each gap between items (and time-to-first-token). With
        ``raise_transport=True`` transport-level failures (connection
        lost/closed, worker draining, stalls, deadline expiry) raise typed
        exceptions instead of yielding an error item — the failover path in
        EndpointClient needs to distinguish them from application errors,
        which are always yielded in-band."""
        req_id = next(self._ids)
        q: asyncio.Queue = asyncio.Queue(maxsize=self.STREAM_QUEUE_CAP)
        self._streams[req_id] = q
        if hasattr(request, "to_dict"):
            payload = request.to_dict()
        elif hasattr(request, "model_dump"):
            payload = request.model_dump(exclude_none=True)
        else:
            payload = request  # any JSON-serializable value
        header = {"id": req_id, "op": "generate", "endpoint": endpoint}
        if context is not None:
            header["request_id"] = context.id
            tenant = getattr(context.context, "tenant", None)
            if tenant:
                header["tenant"] = tenant
        if tracing.enabled():
            # propagate the caller's trace context: the Context's carrier
            # wins (set by the edge/router), contextvar as fallback
            tp = tracing.format_traceparent(
                (context.context.trace if context is not None else None)
                or tracing.current_span()
            )
            if tp is not None:
                header["traceparent"] = tp
        if deadline is not None:
            rem = deadline.remaining()
            if rem is not None:
                header["deadline_ms"] = max(int(rem * 1000), 0)
        await self._send(header, json.dumps(payload).encode())

        monitor: Optional[asyncio.Task] = None
        if context is not None:
            async def watch_cancel():
                await context.context.stopped()
                try:
                    await self._send({"id": req_id, "op": "stop"})
                except ConnectionError:
                    pass

            monitor = asyncio.create_task(watch_cancel())
        try:
            while True:
                gap = inter_item_timeout
                if deadline is not None:
                    gap = deadline.bound(gap)
                if gap is None:
                    kind, data = await q.get()
                else:
                    try:
                        kind, data = await asyncio.wait_for(q.get(), gap)
                    except asyncio.TimeoutError:
                        # stop the worker before reporting: its tokens have
                        # no consumer anymore either way
                        try:
                            await self._send({"id": req_id, "op": "kill"})
                        except (ConnectionError, OSError):
                            pass
                        # inter_item_timeout None means the gap bound came
                        # entirely from the deadline — classify as deadline
                        # even if the timer fired a clock-tick early
                        if deadline is not None and (
                            deadline.expired or inter_item_timeout is None
                        ):
                            msg = f"{DEADLINE_ERROR}: waiting for stream item"
                            if raise_transport:
                                raise DeadlineExceeded(msg) from None
                            yield Annotated.from_error(msg)
                            return
                        msg = (f"worker stalled: no item within "
                               f"{inter_item_timeout:.1f}s")
                        if raise_transport:
                            raise WorkerStalled(msg) from None
                        yield Annotated.from_error(msg)
                        return
                if kind == "item":
                    yield Annotated.from_dict(json.loads(data))
                elif kind == "done":
                    return
                else:
                    info = data if isinstance(data, dict) else {"message": str(data)}
                    msg = str(info.get("message", "remote error"))
                    if raise_transport:
                        if info.get("code") == "deadline":
                            raise DeadlineExceeded(msg)
                        if info.get("code") == "overloaded":
                            raise OverloadedError(
                                msg,
                                queue_depth=int(info.get("queue_depth") or 0),
                                retry_after_ms=int(info.get("retry_after_ms") or 0),
                                tenant=info.get("tenant"),
                            )
                        if info.get("retryable"):
                            raise RetryableRpcError(msg)
                    yield Annotated.from_error(msg)
                    return
        finally:
            if monitor:
                monitor.cancel()
            self._streams.pop(req_id, None)
            self._stall_since.pop(req_id, None)
