"""Direct-dial streaming RPC between clients and workers.

Design delta vs the reference (intentional): the reference pushes requests
through NATS and opens a TCP connect-back for responses (two hops + a broker;
egress/push.rs:37-180, tcp/server.rs). Here discovery (statestore) hands the
client the worker's address and the client dials it directly — request and
response stream ride ONE multiplexed TCP connection with the same framed
codec. Same capability (streaming, cancellation, graceful drain), one less
network hop on every token.

Wire protocol (header JSON + body):
  client→worker: {id, op:"generate", endpoint} body=request JSON
                 {id, op:"stop"|"kill"}        (mid-stream cancellation)
  worker→client: {id, op:"item"}  body=one Annotated dict JSON
                 {id, op:"done"}
                 {id, op:"error", message}
"""

from __future__ import annotations

import asyncio
import itertools
import json
import logging
from typing import Any, AsyncIterator, Dict, Optional, Tuple

from dynamo_tpu.runtime.annotated import Annotated
from dynamo_tpu.runtime.codec import TwoPartMessage, read_frame, write_frame
from dynamo_tpu.runtime.engine import AsyncEngine, Context

logger = logging.getLogger(__name__)


class RpcServer:
    """Serves registered engines over TCP; tracks in-flight requests and
    drains them on stop (reference PushEndpoint semantics)."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0):
        self.host = host
        self.port = port
        self._engines: Dict[str, AsyncEngine] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._inflight: set = set()
        self._draining = False

    def register(self, endpoint: str, engine: AsyncEngine) -> None:
        self._engines[endpoint] = engine

    async def start(self) -> None:
        from dynamo_tpu.runtime.netutil import TrackedServer

        self._server = TrackedServer(self._handle, self.host, self.port)
        self.port = await self._server.start()
        logger.info("rpc server listening on %s:%d", self.host, self.port)

    async def stop(self, drain_timeout: float = 10.0) -> None:
        self._draining = True
        if self._server:
            self._server.close_listener()
        if self._inflight:
            done, pending = await asyncio.wait(self._inflight, timeout=drain_timeout)
            for t in pending:
                t.cancel()
        if self._server:
            await self._server.stop()

    @property
    def inflight_count(self) -> int:
        return len(self._inflight)

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        contexts: Dict[int, Context] = {}
        write_lock = asyncio.Lock()
        conn_tasks: set = set()
        try:
            while True:
                try:
                    frame = await read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    return
                h = json.loads(frame.header)
                op = h.get("op")
                if op == "generate":
                    if self._draining:
                        async with write_lock:
                            await write_frame(writer, TwoPartMessage(
                                json.dumps({"id": h["id"], "op": "error",
                                            "message": "worker draining"}).encode(), b""))
                        continue
                    task = asyncio.create_task(
                        self._serve_request(h, frame.body, writer, write_lock, contexts)
                    )
                    self._inflight.add(task)
                    conn_tasks.add(task)
                    task.add_done_callback(self._inflight.discard)
                    task.add_done_callback(conn_tasks.discard)
                elif op in ("stop", "kill"):
                    ctx = contexts.get(h["id"])
                    if ctx is not None:
                        if op == "kill":
                            ctx.context.kill()
                        else:
                            ctx.context.stop_generating()
        finally:
            # client went away: kill everything it had in flight on this conn
            for ctx in contexts.values():
                ctx.context.kill()
            for t in list(conn_tasks):
                t.cancel()
            writer.close()

    async def _serve_request(self, h, body, writer, write_lock, contexts) -> None:
        req_id = h["id"]
        engine = self._engines.get(h.get("endpoint", ""))

        async def send(header: dict, payload: bytes = b"") -> None:
            async with write_lock:
                await write_frame(writer, TwoPartMessage(json.dumps(header).encode(), payload))

        if engine is None:
            await send({"id": req_id, "op": "error",
                        "message": f"no such endpoint {h.get('endpoint')!r}"})
            return
        try:
            payload = json.loads(body) if body else None
            ctx = Context(payload, request_id=h.get("request_id"))
            contexts[req_id] = ctx
            stream = engine.generate(ctx)
            if hasattr(stream, "__await__"):
                stream = await stream
            async for item in stream:
                d = item.to_dict() if isinstance(item, Annotated) else item
                await send({"id": req_id, "op": "item"}, json.dumps(d).encode())
            await send({"id": req_id, "op": "done"})
        except (ConnectionError, asyncio.CancelledError):
            raise
        except Exception as e:
            logger.exception("rpc handler error (req %s)", req_id)
            try:
                await send({"id": req_id, "op": "error", "message": str(e)})
            except ConnectionError:
                pass
        finally:
            contexts.pop(req_id, None)


class RpcClient:
    """Multiplexed client connection to one worker."""

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._ids = itertools.count(1)
        self._streams: Dict[int, asyncio.Queue] = {}
        self._reader_task: Optional[asyncio.Task] = None
        self._send_lock = asyncio.Lock()
        self.closed = False

    @classmethod
    async def connect(cls, address: str) -> "RpcClient":
        host, _, port = address.rpartition(":")
        c = cls(host or "127.0.0.1", int(port))
        c._reader, c._writer = await asyncio.open_connection(c.host, c.port)
        c._reader_task = asyncio.create_task(c._read_loop())
        return c

    async def close(self) -> None:
        self.closed = True
        if self._reader_task:
            self._reader_task.cancel()
        if self._writer:
            self._writer.close()
        for q in self._streams.values():
            q.put_nowait(("error", "connection closed"))

    async def _read_loop(self) -> None:
        try:
            while True:
                frame = await read_frame(self._reader)
                h = json.loads(frame.header)
                q = self._streams.get(h.get("id"))
                if q is None:
                    continue
                op = h.get("op")
                if op == "item":
                    q.put_nowait(("item", frame.body))
                elif op == "done":
                    q.put_nowait(("done", None))
                elif op == "error":
                    q.put_nowait(("error", h.get("message", "remote error")))
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError):
            self.closed = True
            for q in self._streams.values():
                q.put_nowait(("error", "connection lost"))

    async def _send(self, header: dict, body: bytes = b"") -> None:
        async with self._send_lock:
            await write_frame(self._writer, TwoPartMessage(json.dumps(header).encode(), body))

    async def generate(
        self, endpoint: str, request: Any, context: Optional[Context] = None
    ) -> AsyncIterator[Annotated]:
        """Call a remote endpoint; yields Annotated items. Propagates local
        context stop/kill to the worker."""
        req_id = next(self._ids)
        q: asyncio.Queue = asyncio.Queue()
        self._streams[req_id] = q
        if hasattr(request, "to_dict"):
            payload = request.to_dict()
        elif hasattr(request, "model_dump"):
            payload = request.model_dump(exclude_none=True)
        else:
            payload = request  # any JSON-serializable value
        header = {"id": req_id, "op": "generate", "endpoint": endpoint}
        if context is not None:
            header["request_id"] = context.id
        await self._send(header, json.dumps(payload).encode())

        monitor: Optional[asyncio.Task] = None
        if context is not None:
            async def watch_cancel():
                await context.context.stopped()
                try:
                    await self._send({"id": req_id, "op": "stop"})
                except ConnectionError:
                    pass

            monitor = asyncio.create_task(watch_cancel())
        try:
            while True:
                kind, data = await q.get()
                if kind == "item":
                    yield Annotated.from_dict(json.loads(data))
                elif kind == "done":
                    return
                else:
                    yield Annotated.from_error(str(data))
                    return
        finally:
            if monitor:
                monitor.cancel()
            self._streams.pop(req_id, None)
