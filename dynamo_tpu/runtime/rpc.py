"""Direct-dial streaming RPC between clients and workers.

Design delta vs the reference (intentional): the reference pushes requests
through NATS and opens a TCP connect-back for responses (two hops + a broker;
egress/push.rs:37-180, tcp/server.rs). Here discovery (statestore) hands the
client the worker's address and the client dials it directly — request and
response stream ride ONE multiplexed TCP connection with the same framed
codec. Same capability (streaming, cancellation, graceful drain), one less
network hop on every token.

Wire protocol (header JSON + body):
  client→worker: {id, op:"generate", endpoint, deadline_ms?} body=request JSON
                 {id, op:"stop"|"kill"}        (mid-stream cancellation)
  worker→client: {id, op:"item"}  body=one Annotated dict JSON
                 {id, op:"done"}
                 {id, op:"error", message, code?, retryable?}

``deadline_ms`` is the request's *remaining* budget at send time (relative,
not wall-clock — hosts don't share clocks); the worker sheds requests whose
budget is already spent and stops streams whose budget expires mid-flight.
Error replies carry ``retryable`` (safe to fail over to another instance:
draining, transport trouble) and ``code`` ("deadline" | "draining" |
"unknown_endpoint") so clients can map them without string matching.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import logging
from typing import Any, AsyncIterator, Dict, Optional, Tuple

from dynamo_tpu.runtime import faults
from dynamo_tpu.runtime.annotated import Annotated
from dynamo_tpu.runtime.codec import CodecError, TwoPartMessage, read_frame, write_frame
from dynamo_tpu.runtime.engine import AsyncEngine, Context
from dynamo_tpu.runtime.resilience import (
    DEADLINE_ERROR,
    Deadline,
    DeadlineExceeded,
    RetryableRpcError,
    WorkerStalled,
)

logger = logging.getLogger(__name__)


class RpcServer:
    """Serves registered engines over TCP; tracks in-flight requests and
    drains them on stop (reference PushEndpoint semantics)."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0):
        self.host = host
        self.port = port
        self._engines: Dict[str, AsyncEngine] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._inflight: set = set()
        self._draining = False

    def register(self, endpoint: str, engine: AsyncEngine) -> None:
        self._engines[endpoint] = engine

    async def start(self) -> None:
        from dynamo_tpu.runtime.netutil import TrackedServer

        self._server = TrackedServer(self._handle, self.host, self.port)
        self.port = await self._server.start()
        logger.info("rpc server listening on %s:%d", self.host, self.port)

    async def stop(self, drain_timeout: float = 10.0) -> None:
        self._draining = True
        if self._server:
            self._server.close_listener()
        if self._inflight:
            done, pending = await asyncio.wait(self._inflight, timeout=drain_timeout)
            for t in pending:
                t.cancel()
        if self._server:
            await self._server.stop()

    @property
    def inflight_count(self) -> int:
        return len(self._inflight)

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        contexts: Dict[int, Context] = {}
        write_lock = asyncio.Lock()
        conn_tasks: set = set()
        try:
            while True:
                try:
                    frame = await read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    return
                except CodecError as e:
                    # garbage bytes / corrupt frame: this connection's stream
                    # position is unrecoverable — drop it, leave every other
                    # connection (and this server) untouched
                    logger.warning("malformed rpc frame, closing connection: %s", e)
                    return
                try:
                    h = json.loads(frame.header)
                    if not isinstance(h, dict):
                        raise ValueError("header is not a JSON object")
                except (ValueError, UnicodeDecodeError) as e:
                    logger.warning("malformed rpc header, closing connection: %s", e)
                    return
                op = h.get("op")
                if op == "generate":
                    if h.get("id") is None:
                        async with write_lock:
                            await write_frame(writer, TwoPartMessage(
                                json.dumps({"id": None, "op": "error",
                                            "message": "missing request id"}).encode(),
                                b""))
                        continue
                    if self._draining:
                        async with write_lock:
                            await write_frame(writer, TwoPartMessage(
                                json.dumps({"id": h["id"], "op": "error",
                                            "message": "worker draining",
                                            "code": "draining",
                                            "retryable": True}).encode(), b""))
                        continue
                    task = asyncio.create_task(
                        self._serve_request(h, frame.body, writer, write_lock, contexts)
                    )
                    self._inflight.add(task)
                    conn_tasks.add(task)
                    task.add_done_callback(self._inflight.discard)
                    task.add_done_callback(conn_tasks.discard)
                elif op in ("stop", "kill"):
                    ctx = contexts.get(h.get("id"))
                    if ctx is not None:
                        if op == "kill":
                            ctx.context.kill()
                        else:
                            ctx.context.stop_generating()
        finally:
            # client went away: kill everything it had in flight on this conn
            for ctx in contexts.values():
                ctx.context.kill()
            for t in list(conn_tasks):
                t.cancel()
            writer.close()

    async def _serve_request(self, h, body, writer, write_lock, contexts) -> None:
        req_id = h["id"]
        engine = self._engines.get(h.get("endpoint", ""))

        async def send(header: dict, payload: bytes = b"") -> None:
            async with write_lock:
                await write_frame(writer, TwoPartMessage(json.dumps(header).encode(), payload))

        if engine is None:
            await send({"id": req_id, "op": "error",
                        "message": f"no such endpoint {h.get('endpoint')!r}",
                        "code": "unknown_endpoint"})
            return
        # the client sends its REMAINING budget; re-anchor it to this host's
        # clock. A request that expired in the queue/network is shed before
        # it touches the engine (reference: no analogue — NATS just redelivers)
        deadline: Optional[Deadline] = None
        deadline_ms = h.get("deadline_ms")
        if deadline_ms is not None:
            try:
                deadline = Deadline.after(float(deadline_ms) / 1000.0)
            except (TypeError, ValueError):
                deadline = None
        if deadline is not None and deadline.expired:
            await send({"id": req_id, "op": "error",
                        "message": f"{DEADLINE_ERROR}: expired before start",
                        "code": "deadline"})
            return
        try:
            payload = json.loads(body) if body else None
            ctx = Context(payload, request_id=h.get("request_id"))
            contexts[req_id] = ctx
            stream = engine.generate(ctx)
            if hasattr(stream, "__await__"):
                stream = await stream
            async for item in stream:
                if deadline is not None and deadline.expired:
                    # nobody is waiting for these tokens anymore: stop the
                    # engine and tell the client why the stream ended
                    ctx.context.kill()
                    await send({"id": req_id, "op": "error",
                                "message": f"{DEADLINE_ERROR}: mid-stream",
                                "code": "deadline"})
                    return
                d = item.to_dict() if isinstance(item, Annotated) else item
                await send({"id": req_id, "op": "item"}, json.dumps(d).encode())
            await send({"id": req_id, "op": "done"})
        except (ConnectionError, asyncio.CancelledError):
            raise
        except Exception as e:
            logger.exception("rpc handler error (req %s)", req_id)
            try:
                await send({"id": req_id, "op": "error", "message": str(e)})
            except ConnectionError:
                pass
        finally:
            contexts.pop(req_id, None)


class RpcClient:
    """Multiplexed client connection to one worker."""

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._ids = itertools.count(1)
        self._streams: Dict[int, asyncio.Queue] = {}
        self._reader_task: Optional[asyncio.Task] = None
        self._send_lock = asyncio.Lock()
        self.closed = False

    @classmethod
    async def connect(cls, address: str, timeout: Optional[float] = None) -> "RpcClient":
        host, _, port = address.rpartition(":")
        c = cls(host or "127.0.0.1", int(port))
        dial = faults.open_connection(c.host, c.port, plane="rpc")
        if timeout is not None:
            # asyncio.wait_for, not asyncio.timeout (py3.10 floor)
            try:
                c._reader, c._writer = await asyncio.wait_for(dial, timeout)
            except asyncio.TimeoutError:
                raise WorkerStalled(
                    f"connect to {address} timed out after {timeout:.1f}s"
                ) from None
        else:
            c._reader, c._writer = await dial
        c._reader_task = asyncio.create_task(c._read_loop())
        return c

    async def close(self) -> None:
        self.closed = True
        if self._reader_task:
            self._reader_task.cancel()
        if self._writer:
            self._writer.close()
        for q in self._streams.values():
            q.put_nowait(("error", {"message": "connection closed", "retryable": True}))

    async def _read_loop(self) -> None:
        try:
            while True:
                frame = await read_frame(self._reader)
                h = json.loads(frame.header)
                if not isinstance(h, dict):
                    # same hardening as the server side: a JSON-valid but
                    # non-object header must not kill the reader silently
                    raise ValueError("response header is not a JSON object")
                q = self._streams.get(h.get("id"))
                if q is None:
                    continue
                op = h.get("op")
                if op == "item":
                    q.put_nowait(("item", frame.body))
                elif op == "done":
                    q.put_nowait(("done", None))
                elif op == "error":
                    q.put_nowait(("error", {
                        "message": h.get("message", "remote error"),
                        "code": h.get("code"),
                        "retryable": bool(h.get("retryable")),
                    }))
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError):
            self.closed = True
            for q in self._streams.values():
                q.put_nowait(("error", {"message": "connection lost", "retryable": True}))
        except (CodecError, ValueError):
            # a server speaking garbage is as dead as a closed socket
            logger.warning("malformed frame from worker %s:%d", self.host, self.port)
            self.closed = True
            if self._writer:
                self._writer.close()
            for q in self._streams.values():
                q.put_nowait(("error", {"message": "malformed response frame",
                                        "retryable": True}))

    async def _send(self, header: dict, body: bytes = b"") -> None:
        async with self._send_lock:
            await write_frame(self._writer, TwoPartMessage(json.dumps(header).encode(), body))

    async def generate(
        self,
        endpoint: str,
        request: Any,
        context: Optional[Context] = None,
        deadline: Optional[Deadline] = None,
        inter_item_timeout: Optional[float] = None,
        raise_transport: bool = False,
    ) -> AsyncIterator[Annotated]:
        """Call a remote endpoint; yields Annotated items. Propagates local
        context stop/kill to the worker.

        ``deadline`` bounds the whole stream (and rides the RPC header so
        the worker sheds expired requests); ``inter_item_timeout`` bounds
        each gap between items (and time-to-first-token). With
        ``raise_transport=True`` transport-level failures (connection
        lost/closed, worker draining, stalls, deadline expiry) raise typed
        exceptions instead of yielding an error item — the failover path in
        EndpointClient needs to distinguish them from application errors,
        which are always yielded in-band."""
        req_id = next(self._ids)
        q: asyncio.Queue = asyncio.Queue()
        self._streams[req_id] = q
        if hasattr(request, "to_dict"):
            payload = request.to_dict()
        elif hasattr(request, "model_dump"):
            payload = request.model_dump(exclude_none=True)
        else:
            payload = request  # any JSON-serializable value
        header = {"id": req_id, "op": "generate", "endpoint": endpoint}
        if context is not None:
            header["request_id"] = context.id
        if deadline is not None:
            rem = deadline.remaining()
            if rem is not None:
                header["deadline_ms"] = max(int(rem * 1000), 0)
        await self._send(header, json.dumps(payload).encode())

        monitor: Optional[asyncio.Task] = None
        if context is not None:
            async def watch_cancel():
                await context.context.stopped()
                try:
                    await self._send({"id": req_id, "op": "stop"})
                except ConnectionError:
                    pass

            monitor = asyncio.create_task(watch_cancel())
        try:
            while True:
                gap = inter_item_timeout
                if deadline is not None:
                    gap = deadline.bound(gap)
                if gap is None:
                    kind, data = await q.get()
                else:
                    try:
                        kind, data = await asyncio.wait_for(q.get(), gap)
                    except asyncio.TimeoutError:
                        # stop the worker before reporting: its tokens have
                        # no consumer anymore either way
                        try:
                            await self._send({"id": req_id, "op": "kill"})
                        except (ConnectionError, OSError):
                            pass
                        # inter_item_timeout None means the gap bound came
                        # entirely from the deadline — classify as deadline
                        # even if the timer fired a clock-tick early
                        if deadline is not None and (
                            deadline.expired or inter_item_timeout is None
                        ):
                            msg = f"{DEADLINE_ERROR}: waiting for stream item"
                            if raise_transport:
                                raise DeadlineExceeded(msg) from None
                            yield Annotated.from_error(msg)
                            return
                        msg = (f"worker stalled: no item within "
                               f"{inter_item_timeout:.1f}s")
                        if raise_transport:
                            raise WorkerStalled(msg) from None
                        yield Annotated.from_error(msg)
                        return
                if kind == "item":
                    yield Annotated.from_dict(json.loads(data))
                elif kind == "done":
                    return
                else:
                    info = data if isinstance(data, dict) else {"message": str(data)}
                    msg = str(info.get("message", "remote error"))
                    if raise_transport:
                        if info.get("code") == "deadline":
                            raise DeadlineExceeded(msg)
                        if info.get("retryable"):
                            raise RetryableRpcError(msg)
                    yield Annotated.from_error(msg)
                    return
        finally:
            if monitor:
                monitor.cancel()
            self._streams.pop(req_id, None)
