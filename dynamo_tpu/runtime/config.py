"""Runtime configuration from environment (`DYN_TPU_*`).

Reference parity: `RuntimeConfig` via figment with `DYN_RUNTIME_`/`DYN_` env
prefixes (lib/runtime/src/config.rs:26-180).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional

ENV_PREFIX = "DYN_TPU_"

_TRUTHY = {"1", "true", "yes", "on"}
_FALSY = {"0", "false", "no", "off"}


def env_str(name: str, default: Optional[str] = None) -> Optional[str]:
    return os.environ.get(ENV_PREFIX + name, default)


def env_int(name: str, default: int) -> int:
    raw = os.environ.get(ENV_PREFIX + name)
    if raw is None:
        return default
    try:
        return int(raw)
    except ValueError as e:
        raise ValueError(f"{ENV_PREFIX}{name}={raw!r} is not an integer") from e


def env_float(name: str, default: float) -> float:
    raw = os.environ.get(ENV_PREFIX + name)
    if raw is None:
        return default
    try:
        return float(raw)
    except ValueError as e:
        raise ValueError(f"{ENV_PREFIX}{name}={raw!r} is not a number") from e


def env_bool(name: str, default: bool = False) -> bool:
    raw = os.environ.get(ENV_PREFIX + name)
    if raw is None:
        return default
    low = raw.strip().lower()
    if low in _TRUTHY:
        return True
    if low in _FALSY:
        return False
    raise ValueError(f"{ENV_PREFIX}{name}={raw!r} is not a boolean")


@dataclass
class RuntimeConfig:
    """Process-level runtime settings.

    graceful_shutdown_timeout mirrors DYN_WORKER_GRACEFUL_SHUTDOWN_TIMEOUT
    (lib/runtime/src/worker.rs:59-211).
    """

    statestore_url: str = field(default_factory=lambda: env_str("STATESTORE", "tcp://127.0.0.1:37901"))
    messaging_url: str = field(default_factory=lambda: env_str("MESSAGING", "tcp://127.0.0.1:37902"))
    graceful_shutdown_timeout: float = field(
        default_factory=lambda: env_float("GRACEFUL_SHUTDOWN_TIMEOUT", 30.0)
    )
    response_plane_host: str = field(default_factory=lambda: env_str("RESPONSE_HOST", "127.0.0.1"))
    response_plane_port: int = field(default_factory=lambda: env_int("RESPONSE_PORT", 0))

    @classmethod
    def from_settings(cls) -> "RuntimeConfig":
        return cls()
