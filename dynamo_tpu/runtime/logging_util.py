"""Logging init: `DYN_TPU_LOG` filter env, optional JSONL output.

Reference parity: lib/runtime/src/logging.rs:63-344 (`DYN_LOG`, `DYN_LOGGING_JSONL`,
per-module filter map). Implemented over stdlib logging.
"""

from __future__ import annotations

import json
import logging
import os
import sys

_INITIALIZED = False


class JsonlFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": record.created,
            "level": record.levelname,
            "target": record.name,
            "message": record.getMessage(),
        }
        if record.exc_info:
            out["exception"] = self.formatException(record.exc_info)
        return json.dumps(out)


def init(level: str | None = None) -> None:
    """Idempotent logging init.

    `DYN_TPU_LOG` accepts either a global level (`info`) or a comma list with
    per-module overrides (`info,dynamo_tpu.kv_router=debug`).
    """
    global _INITIALIZED
    if _INITIALIZED:
        return

    spec = level or os.environ.get("DYN_TPU_LOG", "info")
    parts = [p.strip() for p in spec.split(",") if p.strip()]
    root_level = "info"
    overrides: dict[str, str] = {}
    for p in parts:
        if "=" in p:
            mod, lvl = p.split("=", 1)
            overrides[mod.strip()] = lvl.strip()
        else:
            root_level = p

    handler = logging.StreamHandler(sys.stderr)
    from .config import env_bool

    if env_bool("LOGGING_JSONL", False):
        handler.setFormatter(JsonlFormatter())
    else:
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
        )
    def _resolve_level(name: str, source: str) -> int:
        mapped = {"trace": "DEBUG", "warn": "WARNING"}.get(name.lower(), name.upper())
        resolved = logging.getLevelName(mapped)
        if not isinstance(resolved, int):
            raise ValueError(f"unknown log level {name!r} in {source}")
        return resolved

    root = logging.getLogger()
    root.addHandler(handler)
    root.setLevel(_resolve_level(root_level, "DYN_TPU_LOG"))
    for mod, lvl in overrides.items():
        logging.getLogger(mod).setLevel(_resolve_level(lvl, f"DYN_TPU_LOG ({mod})"))
    _INITIALIZED = True
