"""Logging init: `DYN_TPU_LOG` filter env, optional JSONL output.

Reference parity: lib/runtime/src/logging.rs:63-344 (`DYN_LOG`, `DYN_LOGGING_JSONL`,
per-module filter map). Implemented over stdlib logging.
"""

from __future__ import annotations

import json
import logging
import os
import sys

_INITIALIZED = False


class JsonlFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": record.created,
            "level": record.levelname,
            "target": record.name,
            "message": record.getMessage(),
        }
        if record.exc_info:
            out["exception"] = self.formatException(record.exc_info)
        return json.dumps(out)


def init(level: str | None = None) -> None:
    """Idempotent logging init.

    `DYN_TPU_LOG` accepts either a global level (`info`) or a comma list with
    per-module overrides (`info,dynamo_tpu.kv_router=debug`).
    """
    global _INITIALIZED
    if _INITIALIZED:
        return

    spec = level or os.environ.get("DYN_TPU_LOG", "info")
    parts = [p.strip() for p in spec.split(",") if p.strip()]
    root_level = "info"
    overrides: dict[str, str] = {}
    for p in parts:
        if "=" in p:
            mod, lvl = p.split("=", 1)
            overrides[mod.strip()] = lvl.strip()
        else:
            root_level = p

    handler = logging.StreamHandler(sys.stderr)
    if os.environ.get("DYN_TPU_LOGGING_JSONL", "").lower() in {"1", "true", "yes"}:
        handler.setFormatter(JsonlFormatter())
    else:
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
        )
    root = logging.getLogger()
    root.addHandler(handler)
    root.setLevel(root_level.upper())
    for mod, lvl in overrides.items():
        logging.getLogger(mod).setLevel(lvl.upper())
    _INITIALIZED = True
