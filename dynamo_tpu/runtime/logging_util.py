"""Logging init: `DYN_TPU_LOG` filter env, optional JSONL output.

Reference parity: lib/runtime/src/logging.rs:63-344 (`DYN_LOG`, `DYN_LOGGING_JSONL`,
per-module filter map). Implemented over stdlib logging.

Trace correlation: every record emitted inside a request context carries the
request's ``trace_id``/``request_id`` (from the tracing contextvars —
``runtime/tracing.py``). The JSONL formatter adds them as fields; the plain
formatter appends ``[trace=… req=…]`` — so grepping a trace id returns the
request's full log story, interleaved across components, instead of today's
uncorrelated lines.
"""

from __future__ import annotations

import json
import logging
import sys

from dynamo_tpu.runtime.envknobs import env_str

_INITIALIZED = False


class TraceContextFilter(logging.Filter):
    """Stamp ``trace_id``/``request_id`` onto every record from the tracing
    contextvars. A *filter* (not a formatter concern) so both output formats
    — and any operator-attached handler downstream — see the fields.
    Records logged outside any request context get empty strings, keeping
    formatter lookups unconditional."""

    def filter(self, record: logging.LogRecord) -> bool:
        try:
            from dynamo_tpu.runtime import tracing

            trace_id, request_id = tracing.current_ids()
        except Exception:  # logging must never fail on tracing trouble
            trace_id = request_id = None
        record.trace_id = trace_id or ""
        record.request_id = request_id or ""
        return True


class JsonlFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": record.created,
            "level": record.levelname,
            "target": record.name,
            "message": record.getMessage(),
        }
        trace_id = getattr(record, "trace_id", "")
        if trace_id:
            out["trace_id"] = trace_id
        request_id = getattr(record, "request_id", "")
        if request_id:
            out["request_id"] = request_id
        if record.exc_info:
            out["exception"] = self.formatException(record.exc_info)
        return json.dumps(out)


class PlainFormatter(logging.Formatter):
    """The human format, with the trace correlation appended only when a
    record actually has it — quiet startup logs stay untouched."""

    def format(self, record: logging.LogRecord) -> str:
        base = super().format(record)
        trace_id = getattr(record, "trace_id", "")
        request_id = getattr(record, "request_id", "")
        if trace_id or request_id:
            parts = []
            if trace_id:
                parts.append(f"trace={trace_id}")
            if request_id:
                parts.append(f"req={request_id}")
            return f"{base} [{' '.join(parts)}]"
        return base


def init(level: str | None = None) -> None:
    """Idempotent logging init.

    `DYN_TPU_LOG` accepts either a global level (`info`) or a comma list with
    per-module overrides (`info,dynamo_tpu.kv_router=debug`).
    """
    global _INITIALIZED
    if _INITIALIZED:
        return

    spec = level or env_str("DYN_TPU_LOG", "info")
    parts = [p.strip() for p in spec.split(",") if p.strip()]
    root_level = "info"
    overrides: dict[str, str] = {}
    for p in parts:
        if "=" in p:
            mod, lvl = p.split("=", 1)
            overrides[mod.strip()] = lvl.strip()
        else:
            root_level = p

    handler = logging.StreamHandler(sys.stderr)
    handler.addFilter(TraceContextFilter())
    from .config import env_bool

    if env_bool("LOGGING_JSONL", False):
        handler.setFormatter(JsonlFormatter())
    else:
        handler.setFormatter(
            PlainFormatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
        )
    def _resolve_level(name: str, source: str) -> int:
        mapped = {"trace": "DEBUG", "warn": "WARNING"}.get(name.lower(), name.upper())
        resolved = logging.getLevelName(mapped)
        if not isinstance(resolved, int):
            raise ValueError(f"unknown log level {name!r} in {source}")
        return resolved

    root = logging.getLogger()
    root.addHandler(handler)
    root.setLevel(_resolve_level(root_level, "DYN_TPU_LOG"))
    for mod, lvl in overrides.items():
        logging.getLogger(mod).setLevel(_resolve_level(lvl, f"DYN_TPU_LOG ({mod})"))
    _INITIALIZED = True
