"""The core streaming-engine abstraction.

An *engine* turns one request into an async stream of responses. Everything in the
framework — models, preprocessors, routers, network clients — implements this one
interface, so pipelines compose uniformly in-process and across the network.

Reference parity: dynamo's `AsyncEngine` trait and `AsyncEngineContext`
(lib/runtime/src/engine.rs:47-116). The TPU build expresses it with Python asyncio:
an engine is any object with ``async generate(request: Context) -> AsyncIterator``;
cancellation propagates through the shared :class:`Context` rather than a token tree.
"""

from __future__ import annotations

import abc
import asyncio
import uuid
from typing import Any, AsyncIterator, Callable, Generic, Optional, TypeVar

T = TypeVar("T")
U = TypeVar("U")


class EngineContext:
    """Cancellation + identity for one in-flight request.

    Mirrors the reference's AsyncEngineContext (lib/runtime/src/engine.rs:47-86):
    - ``id``       stable request id, propagated across process hops
    - ``stop()``   graceful: the engine should finish the current item and stop
    - ``kill()``   immediate: abandon the stream
    - ``trace``    tracing parent for this request (``runtime/tracing.py``):
      a local Span, a ``(trace_id, span_id)`` wire context extracted from a
      ``traceparent`` header, or None. Riding the context (rather than a
      contextvar) survives engine-thread hops and async-generator plumbing.
    - ``tenant``   QoS tenant id (``runtime/qos.py``), extracted at the
      HTTP edge (``x-tenant-id`` / API-key map) or from the RPC header;
      None on the single-tenant path. Rides the context so admission,
      scheduling, KV budgets, and tracing all attribute to the same id.
    - ``journal``  mid-stream resume journal
      (``runtime/resilience.StreamJournal``), attached by the routing
      client for token-level requests when resume is enabled; None
      otherwise (the zero-overhead off path). The HTTP edge reads its
      ``resumes`` count to attribute a post-resume first chunk as an ITL
      gap instead of admission TTFT.
    """

    __slots__ = ("_id", "_stopped", "_killed", "_stop_event", "trace",
                 "tenant", "journal")

    def __init__(self, request_id: Optional[str] = None):
        self._id = request_id or uuid.uuid4().hex
        self._stopped = False
        self._killed = False
        self._stop_event: Optional[asyncio.Event] = None
        self.trace = None
        self.tenant: Optional[str] = None
        self.journal = None

    @property
    def id(self) -> str:
        return self._id

    def stop_generating(self) -> None:
        self._stopped = True
        if self._stop_event is not None:
            self._stop_event.set()

    def kill(self) -> None:
        self._killed = True
        self._stopped = True
        if self._stop_event is not None:
            self._stop_event.set()

    @property
    def is_stopped(self) -> bool:
        return self._stopped

    @property
    def is_killed(self) -> bool:
        return self._killed

    async def stopped(self) -> None:
        """Await until stop/kill is requested."""
        if self._stopped:
            return
        if self._stop_event is None:
            self._stop_event = asyncio.Event()
        await self._stop_event.wait()


class Context(Generic[T]):
    """A request plus its engine context, flowing through a pipeline.

    Reference: `Context<T>` (lib/runtime/src/pipeline/context.rs). ``map`` rewraps
    the payload keeping the same context; ``transfer`` moves the context onto a new
    payload (used when an operator fully replaces the request).
    """

    __slots__ = ("data", "_ctx")

    def __init__(self, data: T, ctx: Optional[EngineContext] = None, request_id: Optional[str] = None):
        self.data = data
        self._ctx = ctx or EngineContext(request_id)

    @property
    def id(self) -> str:
        return self._ctx.id

    @property
    def context(self) -> EngineContext:
        return self._ctx

    def map(self, fn: Callable[[T], U]) -> "Context[U]":
        return Context(fn(self.data), ctx=self._ctx)

    def transfer(self, data: U) -> "Context[U]":
        return Context(data, ctx=self._ctx)

    def __repr__(self) -> str:  # pragma: no cover
        return f"Context(id={self.id!r}, data={type(self.data).__name__})"


class AsyncEngine(abc.ABC, Generic[T, U]):
    """Engine interface: one request in, an async stream of responses out."""

    @abc.abstractmethod
    def generate(self, request: Context[T]) -> AsyncIterator[U]:
        """Return an async iterator of responses for this request.

        Implementations are normally ``async def generate(...)`` generator
        functions; callers iterate with ``async for``. Implementations must
        observe ``request.context.is_stopped`` between items.
        """

    async def generate_one(self, request: Context[T]) -> U:
        """Convenience: collect exactly the final response of a unary engine."""
        last: Any = _SENTINEL
        async for item in self.generate(request):
            last = item
        if last is _SENTINEL:
            raise RuntimeError(f"engine produced no response for request {request.id}")
        return last


_SENTINEL = object()


class FnEngine(AsyncEngine[T, U]):
    """Adapt a plain async-generator function into an AsyncEngine.

    Reference analogue: the lambda/async-generator fake engines used throughout
    dynamo's tests (lib/runtime/tests/common/engines.rs).
    """

    def __init__(self, fn: Callable[[Context[T]], AsyncIterator[U]], name: str = "fn"):
        self._fn = fn
        self._name = name

    def generate(self, request: Context[T]) -> AsyncIterator[U]:
        return self._fn(request)

    def __repr__(self) -> str:  # pragma: no cover
        return f"FnEngine({self._name})"


async def collect(stream: AsyncIterator[U]) -> list[U]:
    """Drain a response stream into a list (test/aggregation helper)."""
    return [item async for item in stream]
