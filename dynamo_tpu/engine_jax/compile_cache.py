"""Persistent XLA compilation cache.

Real-chip compiles of the serving step functions run 14-15 s each; the
persistent cache makes every compile after the first process launch a
disk load. Mirrors the reference's philosophy of keeping startup cost off
the request path (its engines load prebuilt CUDA binaries; XLA's unit of
reuse is the compiled executable).
"""

from __future__ import annotations

import os

_DEFAULT = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(__file__))), ".jax_cache")


def enable_compile_cache(path: str | None = None) -> str:
    """Point JAX's compilation cache at a repo-local directory.

    Call before the first jit dispatch. DYN_TPU_COMPILE_CACHE overrides the
    location; setting it to "0" disables the cache entirely.
    """
    env = os.environ.get("DYN_TPU_COMPILE_CACHE")
    if env == "0":
        return ""
    target = path or env or _DEFAULT
    os.makedirs(target, exist_ok=True)

    import jax

    jax.config.update("jax_compilation_cache_dir", target)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    return target
