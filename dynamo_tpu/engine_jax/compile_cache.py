"""Persistent XLA compilation cache.

Real-chip compiles of the serving step functions run 14-15 s each; the
persistent cache makes every compile after the first process launch a
disk load. Mirrors the reference's philosophy of keeping startup cost off
the request path (its engines load prebuilt CUDA binaries; XLA's unit of
reuse is the compiled executable).
"""

from __future__ import annotations

import os
import sys
import threading

from dynamo_tpu.runtime.envknobs import env_raw

_DEFAULT = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(__file__))), ".jax_cache")

# process-global count of jitted-program builds (engine step-fn variants,
# counts syncs, inject scatters). A steady-state engine compiles a handful
# at boot and then NEVER again — a climbing count mid-traffic means some
# shape leaked into a jit signature and every bump stalled decode for a
# full compile. Surfaced live as ForwardPassMetrics.jit_recompiles.
_COMPILE_LOCK = threading.Lock()
_COMPILES: dict[str, int] = {}


def record_compile(kind: str = "step", detail: str = "") -> None:
    """Count one jitted-program build (called where engines create a new
    compiled variant — cache misses in their per-shape fn tables).
    ``detail`` carries the triggering variant key / abstract shapes; it
    lands on the profiling timeline (docs/observability.md §Profiling) as
    a ``jit_compile`` event when that plane is armed — a recompile storm
    mid-traffic then shows up ON the capture that measured the stall."""
    with _COMPILE_LOCK:
        _COMPILES[kind] = _COMPILES.get(kind, 0) + 1
    # lazy + constructor-free: processes that never armed DYN_TPU_PROFILE
    # never even import the profiling module from here
    prof = sys.modules.get("dynamo_tpu.runtime.profiling")
    if prof is not None:
        prof.note_event(
            "jit_compile", detail=f"{kind} {detail}".strip(), phase=kind
        )


def compile_count() -> int:
    with _COMPILE_LOCK:
        return sum(_COMPILES.values())


def compile_counts() -> dict[str, int]:
    with _COMPILE_LOCK:
        return dict(_COMPILES)


def enable_compile_cache(path: str | None = None) -> str:
    """Point JAX's compilation cache at a repo-local directory.

    Call before the first jit dispatch. DYN_TPU_COMPILE_CACHE overrides the
    location; setting it to "0" disables the cache entirely.
    """
    env = env_raw("DYN_TPU_COMPILE_CACHE")
    if env == "0":
        return ""
    target = path or env or _DEFAULT
    os.makedirs(target, exist_ok=True)

    import jax

    jax.config.update("jax_compilation_cache_dir", target)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    return target
