"""The TPU serving engine: continuous batching over paged KV in HBM.

This is the framework-native worker the reference delegates to vLLM/SGLang for
(SURVEY.md §2.5, §2.9): a JAX program with fixed batch slots, bucketed prefill,
a single jitted decode step, prefix-cache-aware paged block allocation, and
token streaming across the jit boundary.
"""

from dynamo_tpu.engine_jax.allocator import BlockAllocator, KvEventSink
from dynamo_tpu.engine_jax.engine import (
    EngineConfig,
    JaxServingEngine,
    build_jax_serving_engine,
)

__all__ = [
    "BlockAllocator",
    "KvEventSink",
    "EngineConfig",
    "JaxServingEngine",
    "build_jax_serving_engine",
]
