"""Self-drafting for speculative decoding: prompt-lookup / n-gram proposals.

Decode is weight-stream-bound (docs/decode_performance.md): a dispatch that
verifies k drafted tokens plus samples one fresh token streams the weights
ONCE for up to k+1 emitted tokens. The drafter proposes those k tokens with
no second model — it indexes the sequence's own token stream (prompt +
generated so far) by trailing n-gram and, when the current suffix has
occurred before, proposes the tokens that followed that earlier occurrence.
Repetition-heavy workloads (multi-turn chat quoting context, code edits,
extraction/summarization copying spans) accept most of the proposal; random
text accepts almost none, and the engine falls back to the plain pipelined
decode step whenever no lane can draft, so the worst case costs nothing.

Correctness never depends on the drafts: the jit ``verify`` variant
(engine_jax/sampling.py ``speculative_targets``) samples the engine's OWN
target token at every position and the engine keeps exactly the drafted
prefix that MATCHES those targets (plus the first non-matching target as the
bonus token) — so greedy speculative output is bitwise identical to
non-speculative greedy output, and sampled output follows the exact
autoregressive distribution (each emitted token was drawn from the model's
conditional at its position; drafts only decide how many survive per
dispatch).

Env knobs (PR3-style clamped parsers — malformed values degrade to safe
defaults, never to a crash or an accidental always-on):

- ``DYN_TPU_SPEC_K``      draft tokens verified per decode dispatch
                          (0 = speculation off, the default; clamped to
                          [0, MAX_SPEC_K]).
- ``DYN_TPU_SPEC_NGRAM``  longest trailing n-gram probed for a match
                          (clamped to [1, 8]; shorter grams are probed as
                          fallback down to MIN_NGRAM).
- ``DYN_TPU_KV_DTYPE``    KV page storage dtype: ``bf16`` (native, default)
                          or ``int8`` (quantized pages + per-block scale
                          tables, engine_jax/allocator.py / models/llama.py).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from dynamo_tpu.runtime.envknobs import env_raw

# hard bound on draft length: each draft position adds a verified lm_head
# column and a KV write; past ~16 the acceptance tail can't pay for the
# extra FLOPs even at high match rates
MAX_SPEC_K = 16
MIN_NGRAM = 2

# adaptive dormancy: a sequence whose drafts keep getting rejected stops
# proposing (the engine then runs plain pipelined decode for it) — this is
# what bounds the adversarial-workload overhead near zero
DORMANT_MIN_DRAFTED = 48
DORMANT_ACCEPT_FLOOR = 0.08


def env_spec_k(default: int = 0) -> int:
    """``DYN_TPU_SPEC_K`` with clamping: unset/malformed → default, negative
    → 0 (off), oversized → MAX_SPEC_K."""
    raw = env_raw("DYN_TPU_SPEC_K")
    if raw is None:
        return default
    try:
        v = int(raw)
    except ValueError:
        return default
    return max(0, min(v, MAX_SPEC_K))


def env_spec_ngram(default: int = 3) -> int:
    """``DYN_TPU_SPEC_NGRAM`` clamped to [1, 8]."""
    raw = env_raw("DYN_TPU_SPEC_NGRAM")
    if raw is None:
        return default
    try:
        v = int(raw)
    except ValueError:
        return default
    return max(1, min(v, 8))


def env_kv_dtype(default: str = "bf16") -> str:
    """``DYN_TPU_KV_DTYPE``: only ``int8`` activates quantized pages; any
    other value (including malformed) is the native-dtype default — a typo
    must never silently quantize a serving fleet's KV."""
    raw = (env_raw("DYN_TPU_KV_DTYPE") or "").strip().lower()
    return "int8" if raw == "int8" else default


class NgramDrafter:
    """Per-sequence suffix index over prompt + generated tokens.

    ``_index[n]`` maps each n-gram (as a tuple) to the position *after* its
    most recent occurrence; :meth:`extend` keeps the maps current as tokens
    are emitted (O(ngram_max) per token, a handful of dict writes).
    :meth:`draft` probes the longest gram first — longer matches predict
    longer accepted runs — and proposes the tokens that followed the match.

    The drafter owns its copy of the token stream (``_toks``); preemption
    re-admissions don't disturb it because the logical stream (prompt +
    generated, concatenated) is append-only for the life of the request.
    """

    __slots__ = ("k", "ngram_max", "_toks", "_index", "drafted", "accepted")

    def __init__(self, prompt: Sequence[int], k: int, ngram_max: int = 3):
        self.k = k
        self.ngram_max = max(MIN_NGRAM, min(ngram_max, 8))
        self._toks: List[int] = []
        # one map per gram length: tuple(gram) -> (position after the most
        # recent occurrence, position after the one before it). Two entries
        # because the stream's live suffix registers ITSELF on every append —
        # a draft for that suffix needs the occurrence before it.
        self._index: Dict[
            int, Dict[Tuple[int, ...], Tuple[int, Optional[int]]]
        ] = {n: {} for n in range(MIN_NGRAM, self.ngram_max + 1)}
        self.drafted = 0  # draft tokens handed to verify dispatches
        self.accepted = 0  # of those, how many matched the sampled target
        self.extend(prompt)

    def __len__(self) -> int:
        return len(self._toks)

    @property
    def accept_rate(self) -> float:
        return self.accepted / self.drafted if self.drafted else 0.0

    @property
    def dormant(self) -> bool:
        return (
            self.drafted >= DORMANT_MIN_DRAFTED
            and self.accept_rate < DORMANT_ACCEPT_FLOOR
        )

    def extend(self, tokens: Sequence[int]) -> None:
        """Append emitted tokens, registering every n-gram they complete.
        Later occurrences overwrite earlier ones (the most recent match is
        the best predictor of what follows the current suffix)."""
        toks = self._toks
        for t in tokens:
            toks.append(int(t))
            end = len(toks)
            for n in range(MIN_NGRAM, self.ngram_max + 1):
                if end >= n:
                    d = self._index[n]
                    key = tuple(toks[end - n:end])
                    prior = d.get(key)
                    d[key] = (end, prior[0] if prior is not None else None)

    def note_result(self, drafted: int, accepted: int) -> None:
        self.drafted += drafted
        self.accepted += accepted

    def would_draft(self) -> bool:
        """Cheap pre-dispatch gate: does the index hold a prior (non-self)
        occurrence of any trailing gram? Same lookups as :meth:`draft`
        without building the proposal. The engine consults this BEFORE
        draining the pipelined decode chunk — a verify dispatch is only
        worth the drain if some lane can plausibly propose, so workloads
        whose streams never repeat (the adversarial case) keep the plain
        pipelined decode path at the cost of a few dict probes per step.
        The answer is stale by the in-flight decode chunk (up to
        ``decode_steps`` tokens not yet appended), so a repetition that
        first completes inside that chunk engages speculation up to one
        chunk late — a conservative miss, never a wrong answer; once the
        chunk drains and the match is indexed, every later probe sees it."""
        if self.dormant:
            return False
        toks = self._toks
        end = len(toks)
        for n in range(self.ngram_max, MIN_NGRAM - 1, -1):
            if end < n:
                continue
            hit = self._index[n].get(tuple(toks[end - n:end]))
            if hit is None:
                continue
            pos = hit[0] if hit[0] < end else hit[1]
            if pos is not None and pos < end:
                return True
        return False

    def draft(self) -> Optional[List[int]]:
        """Propose up to ``k`` continuation tokens for the current suffix,
        longest matching gram first. None = no proposal (no gram match, the
        match points at the stream's live end, or the drafter went dormant
        after sustained rejection)."""
        if self.dormant:
            return None
        toks = self._toks
        end = len(toks)
        for n in range(self.ngram_max, MIN_NGRAM - 1, -1):
            if end < n:
                continue
            hit = self._index[n].get(tuple(toks[end - n:end]))
            if hit is None:
                continue
            # the live suffix always matches itself (registered on append):
            # skip to the occurrence before it
            pos = hit[0] if hit[0] < end else hit[1]
            if pos is None or pos >= end:
                continue
            out = toks[pos:pos + self.k]
            if out:
                return list(out)
        return None
